"""DataNode — checksummed block storage + pipelined transfer.

≈ ``org.apache.hadoop.hdfs.server.datanode.{DataNode,DataXceiver,
FSDataset,BlockReceiver,BlockSender}`` (reference: DataNode.java 2133 LoC).
Contracts reproduced:

- blocks live as ``blk_<id>`` files with a sidecar ``.meta`` of per-chunk
  CRC32s (≈ the checksum meta file); reads verify and raise on corruption
  (ChecksumException), which also triggers client replica failover;
- write pipeline: the client streams a block to the FIRST target in
  bounded chunks (open/write_chunk/commit), each node forwards
  downstream then appends, acks propagate back up the chain
  (DN→DN→DN chained pipeline of BlockReceiver; ≈ DataTransferProtocol
  WRITE_BLOCK). Reads stream the same way (read_block_chunk ≈
  BlockSender) with chunk-aligned checksum verification — whole blocks
  never ride one RPC payload in either direction;
- heartbeat loop: register → initial block report → periodic heartbeats
  that carry back NameNode commands (replicate/delete ≈
  DNA_TRANSFER/DNA_INVALIDATE), full block reports on request/interval.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Any

from tpumr.io import compress
from tpumr.io.fdcache import FdCache
from tpumr.ipc.rpc import RpcClient, RpcServer

CHUNK = 64 * 1024


class ChecksumError(IOError):
    pass


class BlockStore:
    """On-disk block files + chunk checksums (≈ FSDataset).

    The read path is served from a pinned-LRU fd cache (tpumr.io.fdcache,
    the shuffle server's engine) plus an in-memory meta cache: a block
    streamed out as N chunks used to cost N×(open block + open/parse
    .meta) — now chunk 2..N is one ``pread`` and a dict hit. Every
    mutation (write/finalize/abort/delete) invalidates both caches:
    ``os.replace`` swaps the inode under the path, and a cached fd would
    otherwise keep serving the OLD block's bytes forever."""

    def __init__(self, data_dir: str, fd_capacity: int = 64) -> None:
        self.dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self._fds = FdCache(capacity=fd_capacity)
        #: block_id -> parsed .meta ({"len", "sums"}); bounded by the
        #: same capacity as the fd cache (metas are ~the hot set)
        self._meta: "dict[int, dict]" = {}
        self._meta_mu = threading.Lock()
        self._meta_cap = max(16, int(fd_capacity) * 4)

    def _path(self, block_id: int) -> str:
        return os.path.join(self.dir, f"blk_{block_id}")

    def _invalidate(self, block_id: int) -> None:
        """Drop cached fd + meta for one block (call on ANY mutation)."""
        self._fds.invalidate(self._path(block_id))
        with self._meta_mu:
            self._meta.pop(block_id, None)

    def _load_meta(self, block_id: int) -> dict:
        with self._meta_mu:
            meta = self._meta.get(block_id)
        if meta is not None:
            return meta
        with open(self._path(block_id) + ".meta") as f:
            meta = json.load(f)
        with self._meta_mu:
            while len(self._meta) >= self._meta_cap:
                self._meta.pop(next(iter(self._meta)))
            self._meta[block_id] = meta
        return meta

    def write(self, block_id: int, data: bytes) -> None:
        sums = [zlib.crc32(data[i:i + CHUNK])
                for i in range(0, max(len(data), 1), CHUNK)]
        tmp = self._path(block_id) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        with open(tmp + ".meta", "w") as f:
            json.dump({"len": len(data), "sums": sums}, f)
        os.replace(tmp + ".meta", self._path(block_id) + ".meta")
        os.replace(tmp, self._path(block_id))
        self._invalidate(block_id)

    def read(self, block_id: int, offset: int = 0,
             length: int = -1) -> bytes:
        path = self._path(block_id)
        if not os.path.exists(path):
            raise FileNotFoundError(f"block {block_id} not stored here")
        with open(path, "rb") as f:
            data = f.read()
        with open(path + ".meta") as f:
            meta = json.load(f)
        sums = [zlib.crc32(data[i:i + CHUNK])
                for i in range(0, max(len(data), 1), CHUNK)]
        if meta["len"] != len(data) or meta["sums"] != sums:
            raise ChecksumError(f"block {block_id} fails checksum")
        if length < 0:
            length = len(data) - offset
        return data[offset:offset + length]

    def read_range(self, block_id: int, offset: int,
                   length: int) -> "tuple[bytes, int]":
        """Range read verifying ONLY the covering checksum chunks (the
        reference's chunk-aligned verification in BlockSender): a
        streaming reader never re-reads or re-hashes the whole block
        per chunk. Served via the fd/meta caches — a multi-chunk stream
        pays one open + one meta parse total, then a ``pread`` per
        chunk (stateless, so the reactor's pool threads serve many
        clients off the same fd concurrently). Returns
        (data, block_length)."""
        path = self._path(block_id)
        try:
            meta = self._load_meta(block_id)
        except FileNotFoundError:
            raise FileNotFoundError(
                f"block {block_id} not stored here") from None
        total = meta["len"]
        offset = max(0, offset)
        length = max(0, min(length, total - offset))
        if length == 0:
            return b"", total
        c0 = offset // CHUNK
        c1 = (offset + length - 1) // CHUNK
        try:
            covering = self._fds.pread(
                path, (c1 - c0 + 1) * CHUNK, c0 * CHUNK)
        except FileNotFoundError:
            # meta cached but block deleted under us: drop stale meta
            self._invalidate(block_id)
            raise FileNotFoundError(
                f"block {block_id} not stored here") from None
        sums = [zlib.crc32(covering[i:i + CHUNK])
                for i in range(0, len(covering), CHUNK)]
        if sums != meta["sums"][c0:c1 + 1]:
            raise ChecksumError(f"block {block_id} fails checksum "
                                f"(chunks {c0}..{c1})")
        lo = offset - c0 * CHUNK
        return covering[lo:lo + length], total

    # ------------------------------------------------ streaming receive

    def open_stream(self, block_id: int) -> str:
        """Begin a streamed block write: appends go to the .tmp file,
        finalize_stream checksums + atomically installs it."""
        tmp = self._path(block_id) + ".tmp"
        open(tmp, "wb").close()
        return tmp

    def append_stream(self, block_id: int, data: bytes) -> None:
        with open(self._path(block_id) + ".tmp", "ab") as f:
            f.write(data)

    def finalize_stream(self, block_id: int) -> int:
        """Compute chunk CRCs from the streamed file (one bounded-memory
        re-read), fsync, install block + meta. Returns the length."""
        tmp = self._path(block_id) + ".tmp"
        sums = []
        total = 0
        with open(tmp, "rb") as f:
            while True:
                piece = f.read(CHUNK)
                if not piece and total > 0:
                    break
                sums.append(zlib.crc32(piece))
                total += len(piece)
                if len(piece) < CHUNK:
                    break
        with open(tmp, "ab") as f:
            f.flush()
            os.fsync(f.fileno())
        with open(tmp + ".meta", "w") as f:
            json.dump({"len": total, "sums": sums}, f)
        os.replace(tmp + ".meta", self._path(block_id) + ".meta")
        os.replace(tmp, self._path(block_id))
        self._invalidate(block_id)
        return total

    def abort_stream(self, block_id: int) -> None:
        for suffix in (".tmp", ".tmp.meta"):
            try:
                os.remove(self._path(block_id) + suffix)
            except FileNotFoundError:
                pass

    def delete(self, block_id: int) -> None:
        self._invalidate(block_id)
        for suffix in ("", ".meta"):
            try:
                os.remove(self._path(block_id) + suffix)
            except FileNotFoundError:
                pass

    def corrupt_replica(self, block_id: int) -> bool:
        """Flip one byte mid-file in the ON-DISK replica (chaos/test
        hook — the ``block_corrupt`` scenario's bit-rot model). The
        sidecar .meta is left intact, so the next read or scanner pass
        fails CRC verification exactly like real disk rot. Caches are
        invalidated so the flip is visible immediately, not after the
        cached fd ages out. Returns False when the block isn't here."""
        path = self._path(block_id)
        try:
            size = os.path.getsize(path)
        except OSError:
            return False
        off = size // 2
        fd = os.open(path, os.O_RDWR)
        try:
            old = os.pread(fd, 1, off)
            if not old:
                return False
            os.pwrite(fd, bytes([old[0] ^ 0xFF]), off)
            os.fsync(fd)
        finally:
            os.close(fd)
        self._invalidate(block_id)
        return True

    def blocks(self) -> list[tuple[int, int]]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("blk_") and not name.endswith(".meta") \
                    and not name.endswith(".tmp"):
                bid = int(name[4:])
                out.append((bid, os.path.getsize(os.path.join(self.dir,
                                                              name))))
        return out

    def used(self) -> int:
        return sum(size for _, size in self.blocks())


class DataNode:
    def __init__(self, nn_host: str, nn_port: int, data_dir: str,
                 conf: Any, host: str = "127.0.0.1", port: int = 0) -> None:
        self.conf = conf
        self.store = BlockStore(
            data_dir,
            fd_capacity=int(conf.get("tdfs.datanode.fdcache.capacity",
                                     64)))
        from tpumr.security import rpc_secret
        self._secret = rpc_secret(conf)
        self.nn = RpcClient(nn_host, nn_port, secret=self._secret)
        self.capacity = int(conf.get("tdfs.datanode.capacity",
                                     1 << 40))
        self.heartbeat_s = float(conf.get("tdfs.datanode.heartbeat.s", 1.0))
        # block read/write path metrics — byte + latency distributions
        # and a live concurrent-reader gauge, the series the bench_dfs
        # read-throughput SLO is judged against
        from tpumr.metrics import MetricsSystem
        from tpumr.metrics.histogram import BYTES
        self.metrics = MetricsSystem("datanode")
        self._mreg = self.metrics.new_registry("datanode")
        self._read_bytes = self._mreg.histogram("dn_read_bytes",
                                                bounds=BYTES)
        self._read_seconds = self._mreg.histogram("dn_read_seconds")
        self._write_bytes = self._mreg.histogram("dn_write_bytes",
                                                 bounds=BYTES)
        self._write_seconds = self._mreg.histogram("dn_write_seconds")
        self._readers = 0
        self._mreg.set_gauge("dn_readers", lambda: self._readers)
        # bounded per-block read-frequency sketch (SpaceSaving), its
        # top slice piggybacked on every heartbeat for the NameNode's
        # cluster-wide hot-block table
        from tpumr.dfs.hotblocks import SpaceSaving
        self._hot = SpaceSaving(
            k=int(conf.get("tpumr.dn.hotblocks.k", 64)))
        self._hot_top = int(conf.get("tpumr.dn.hotblocks.top", 16))
        self._hot_lock = threading.Lock()
        # per-heartbeat exponential decay so the sketch follows the
        # CURRENT read mix (the NN cool-down depends on hot shares
        # actually falling); factor chosen so counts halve every
        # halflife.s seconds of heartbeats; 0 disables
        halflife = float(conf.get("tpumr.dn.hotblocks.halflife.s", 60.0))
        self._hot_decay = (0.5 ** (self.heartbeat_s / halflife)
                           if halflife > 0 else 1.0)
        self._server = RpcServer(self, host=host, port=port, secret=self._secret)
        # block reads are read-only + idempotent: exempt them from the
        # server's dedup/replay cache so re-sent reads never pin whole
        # chunk payloads in the reply cache (same idiom as the shuffle
        # server's get_map_output)
        self._server.uncached_methods = {"read_block", "read_block_chunk",
                                         "block_checksum"}
        self._server.metrics = self.metrics.new_registry("rpc")
        # Personal-credential callers (user keys, delegation tokens)
        # reach block data ONLY with a NameNode-minted per-block access
        # stamp (≈ the reference's BlockToken split): the frame is
        # authenticated statelessly, the GATE below demands the stamp.
        # Cluster-secret daemons (NN commands, peer replication) bypass.
        self._server.token_stateless = True
        self._server.request_gate = self._gate_block_access
        self._stop = threading.Event()
        self._hb = threading.Thread(target=self._heartbeat_loop,
                                    name="dn-heartbeat", daemon=True)
        self._peer_clients: dict[str, RpcClient] = {}
        self._lock = threading.Lock()
        #: in-flight streamed uploads: block_id -> {downstream, ts}
        self._uploads: dict[int, dict] = {}
        #: periodic CRC verification of every stored block ≈
        #: DataBlockScanner (reference default: one full pass per 3
        #: weeks; here per-period sweep, 0 disables)
        self.scan_period_s = float(conf.get("tdfs.datanode.scan.period.s",
                                            6 * 3600))
        self._scanner = threading.Thread(target=self._scan_loop,
                                         name="dn-block-scanner",
                                         daemon=True)
        self._http: Any = None
        self._http_port = int(conf.get("tpumr.dn.http.port", -1))
        self.sampler: Any = None
        #: fleet slot (the ``d<n>`` of the targeted ``dn.crash.d<n>``
        #: chaos seam) — -1 when not run under a mini cluster/scenario
        self.fi_index = -1
        #: monotonic deadline while "partitioned away" (``dn.partition``
        #: seam): heartbeats are skipped until then — the process stays
        #: alive and KEEPS SERVING reads, the NN is left to expire it
        #: and fold the rejoin through the re-register + block report
        self._partition_until = 0.0
        self.killed = False

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "DataNode":
        self._server.start()
        self._register()
        self._hb.start()
        if self.scan_period_s > 0:
            self._scanner.start()
        if self._http_port >= 0:
            self._http = self._build_http(self._http_port).start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.sampler is not None:
            self.sampler.stop()
        if self._http is not None:
            self._http.stop()
        self._server.stop()

    def kill(self) -> None:
        """Hard-kill (≈ SIGKILL): the RPC server drops mid-request —
        in-flight reads and pipeline writes fail on the wire, nothing
        deregisters, and the NameNode is left to expire the node and
        re-replicate. The storage dir survives, so a later DataNode on
        the same dir rejoins with its old replicas via block report."""
        self.killed = True
        self._stop.set()
        self._server.stop()

    @property
    def http_url(self) -> "str | None":
        return self._http.url if self._http is not None else None

    def _build_http(self, port: int):
        """Uniform daemon status surface (/metrics, /metrics/prom,
        /stacks //flame under tpumr.prof.enabled) — the same scraper
        config that covers the mapred daemons and the NN now covers
        datanodes too; today the datanode served no status page at all."""
        from tpumr.http import StatusHttpServer
        srv = StatusHttpServer("datanode", port=port)
        srv.attach_metrics(self.metrics)
        from tpumr.metrics.sampler import StackSampler
        self.sampler = StackSampler.from_conf(self.conf, self.metrics)
        if self.sampler is not None:
            self.sampler.start()
            self.sampler.attach_http(srv)

        def hotblocks(q: dict) -> dict:
            with self._hot_lock:
                return self._hot.to_wire(int(q.get("n", self._hot_top)))

        srv.add_raw("hotblocks", hotblocks)

        def summary(q: dict) -> dict:
            blocks = self.store.blocks()
            return {"addr": self.addr, "blocks": len(blocks),
                    "used": sum(s for _, s in blocks),
                    "capacity": self.capacity,
                    "readers": self._readers}

        srv.add_json("datanode", summary)
        return srv

    @property
    def addr(self) -> str:
        host, port = self._server.address
        return f"{host}:{port}"

    def _register(self) -> None:
        self.nn.call("register_datanode", self.addr, self.capacity)
        invalid = self.nn.call("block_report", self.addr,
                               [list(b) for b in self.store.blocks()])
        # the report's return is the NN-driven invalidation channel
        # (orphans of files deleted while we were down, replicas the NN
        # dropped): act on it, or the stale replicas — and any cached
        # fds onto them — live here forever (delete() invalidates the
        # fd/meta caches, closing the fd-cache staleness hole)
        for bid in invalid or []:
            try:
                self.store.delete(int(bid))
            except (TypeError, ValueError, OSError):
                continue

    def _peer(self, addr: str) -> RpcClient:
        with self._lock:
            cli = self._peer_clients.get(addr)
            if cli is None:
                host, port = addr.rsplit(":", 1)
                cli = self._peer_clients[addr] = RpcClient(host, int(port), secret=self._secret)
            return cli

    # ------------------------------------------------------------ heartbeat

    def hot_wire(self) -> dict:
        """The read-frequency slice piggybacked on each heartbeat: the
        sketch's top entries + stream total, bounded by
        tpumr.dn.hotblocks.top regardless of how hot the node runs."""
        with self._hot_lock:
            return self._hot.to_wire(self._hot_top)

    def _heartbeat_loop(self) -> None:
        from tpumr.utils.fi import fires
        while not self._stop.wait(self.heartbeat_s):
            if fires(f"dn.crash.d{self.fi_index}", self.conf) \
                    or fires("dn.crash", self.conf):
                # BEHAVIORAL churn seam: hard-kill mid-beat — in-flight
                # reads/pipeline writes die on the wire, nothing
                # deregisters; NN expiry + re-replication (and client
                # replica failover) are the quarry's predator
                self.kill()
                return
            if fires("dn.partition", self.conf):
                # heartbeat silence WITHOUT process death: reads keep
                # being served while the NN expires us; the rejoin goes
                # through dn_heartbeat's "register" → block report
                self._partition_until = time.monotonic() + float(
                    self.conf.get("tpumr.fi.dn.partition.ms", 3000)) \
                    / 1000.0
            if self._hot_decay < 1.0:
                with self._hot_lock:
                    self._hot.decay(self._hot_decay)
            if time.monotonic() < self._partition_until:
                continue
            try:
                cmds = self.nn.call("dn_heartbeat", self.addr,
                                    self.store.used(), self.capacity,
                                    len(self.store.blocks()),
                                    self.hot_wire())
                for cmd in cmds:
                    self._apply_command(cmd)
            except Exception:  # noqa: BLE001 — NN briefly unreachable
                pass
            # purge streamed uploads abandoned by dead clients (their
            # temp files would otherwise live forever)
            cutoff = time.monotonic() - float(
                self.conf.get("tdfs.upload.stale.s", 600))
            with self._lock:
                stale = [bid for bid, up in self._uploads.items()
                         if up["ts"] < cutoff]
            for bid in stale:
                try:
                    self.abort_block_stream(bid)
                except Exception:  # noqa: BLE001
                    pass

    # ------------------------------------------------------------ scanner

    def scan_once(self) -> "list[int]":
        """One verification sweep over every stored block; corrupt ones
        are reported to the NameNode (which drops the replica — unless it
        is the last — and re-replicates from a good copy). Returns the
        corrupt block ids found."""
        bad = []
        for bid, _size in self.store.blocks():
            if self._stop.is_set():
                break
            try:
                self.store.read(bid)  # full read = CRC verification
            except ChecksumError:
                bad.append(bid)
                try:
                    self.nn.call("report_bad_block", bid, self.addr)
                except Exception:  # noqa: BLE001 — retried next sweep
                    pass
            except FileNotFoundError:
                continue  # deleted mid-scan
        return bad

    def _scan_loop(self) -> None:
        while not self._stop.wait(self.scan_period_s):
            try:
                self.scan_once()
            except Exception:  # noqa: BLE001 — scanner must survive
                pass

    def _apply_command(self, cmd: dict) -> None:
        kind = cmd.get("type")
        if kind == "delete":
            self.store.delete(cmd["block_id"])
        elif kind == "replicate":
            bid = cmd["block_id"]
            try:
                data = self.store.read(bid)
            except (FileNotFoundError, ChecksumError):
                return
            for target in cmd["targets"]:
                try:
                    self._peer(target).call("write_block", bid, data, [])
                except Exception:  # noqa: BLE001
                    continue
        elif kind == "register":
            self._register()

    # ------------------------------------------------------------ access gate

    #: method -> required access mode; every entry takes block_id first
    _GATED = {"read_block": "r", "read_block_chunk": "r",
              "block_checksum": "r", "write_block": "w",
              "open_block_stream": "w", "write_block_chunk": "w",
              "commit_block_stream": "w", "abort_block_stream": "w"}

    def _gate_block_access(self, req: dict, verified_user, job_scoped):
        """Pre-dispatch enforcement (rpc request_gate): personal-scoped
        callers must present a live NameNode stamp bound to (user,
        block, mode). Raw block ids are guessable integers — without
        this, a canceled token could read/corrupt arbitrary blocks until
        its max lifetime."""
        if verified_user is None:
            return                      # cluster-secret daemon caller
        from tpumr.ipc.rpc import RpcAuthError
        method = str(req.get("method", ""))
        mode = self._GATED.get(method)
        if mode is None:
            if method in ("get_protocol_version",):
                return
            raise RpcAuthError(
                f"method {method!r} is not available to "
                "personal-credential callers")
        params = req.get("params") or []
        from tpumr.security.tokens import check_block_access
        if not params or not check_block_access(
                self._secret, req.get("access"), verified_user,
                params[0], mode):
            raise RpcAuthError(
                "block access denied: missing/expired/mismatched "
                "NameNode access stamp")

    # ------------------------------------------------------------ transfer RPC

    def _maybe_rot(self, block_id: int) -> None:
        """``dn.read.corrupt[.b<id>]`` chaos seam: model bit-rot by
        flipping a byte in the on-disk replica just before serving it —
        the UNMODIFIED read path must then fail CRC verification, the
        client fails over and reports the bad block, and the NN drops
        this replica and re-replicates. Readers never see the rot."""
        from tpumr.utils.fi import fires
        if fires(f"dn.read.corrupt.b{block_id}", self.conf) \
                or fires("dn.read.corrupt", self.conf):
            self.store.corrupt_replica(block_id)

    def _note_read(self, block_id: int, n: int, t0: float) -> None:
        self._read_bytes.observe(n)
        self._read_seconds.observe(time.monotonic() - t0)
        with self._hot_lock:
            self._hot.offer(str(block_id))

    def write_block(self, block_id: int, data: bytes,
                    downstream: list[str]) -> None:
        """Pipelined write: forward downstream FIRST, then store locally —
        an ack only returns once the whole chain stored the block
        (≈ BlockReceiver's chained pipeline with downstream acks)."""
        if downstream:
            self._peer(downstream[0]).call("write_block", block_id, data,
                                           downstream[1:])
        t0 = time.monotonic()
        self.store.write(block_id, data)
        self._write_bytes.observe(len(data))
        self._write_seconds.observe(time.monotonic() - t0)
        self.nn.call("block_received", self.addr, block_id, len(data))

    def read_block(self, block_id: int, offset: int = 0,
                   length: int = -1) -> bytes:
        self._maybe_rot(block_id)
        t0 = time.monotonic()
        self._readers += 1
        try:
            data = self.store.read(block_id, offset, length)
        finally:
            self._readers -= 1
        self._note_read(block_id, len(data), t0)
        return data

    #: server-side cap per streamed-transfer RPC — bounds datanode
    #: memory per request regardless of client asks (the streaming
    #: re-design of DataTransferProtocol's op READ_BLOCK: payloads move
    #: as bounded chunks, never whole blocks per response)
    MAX_CHUNK_BYTES = 4 << 20

    def read_block_chunk(self, block_id: int, offset: int,
                         max_bytes: int, wire: str = "none") -> dict:
        """One bounded chunk of a block + its total length; checksums
        verified for the covering CRC chunks only. ``wire`` is a codec
        the CLIENT offers (tdfs.read.wire.codec) — when it pays, the
        payload ships compressed with ``wire`` set in the response and
        the client decodes; sizes/offsets stay payload-relative. Old
        clients omit the param and always get raw bytes."""
        self._maybe_rot(block_id)
        n = max(0, min(int(max_bytes), self.MAX_CHUNK_BYTES))
        t0 = time.monotonic()
        self._readers += 1
        try:
            data, total = self.store.read_range(block_id, int(offset), n)
        finally:
            self._readers -= 1
        self._note_read(block_id, len(data), t0)
        out = {"data": data, "total": total}
        compress.wire_compress(out, compress.wire_codec_or_none(wire))
        return out

    # streamed pipelined write ≈ DataTransferProtocol op WRITE_BLOCK:
    # chunks relay downstream FIRST (same ordering as write_block), each
    # ack returns once the whole chain appended; commit finalizes the
    # chain from the tail up so a successful return means every replica
    # is installed. Session state is (block_id, downstream) — one
    # concurrent upload per block per node, like the reference's
    # single-writer block lease.

    def open_block_stream(self, block_id: int,
                          downstream: "list[str]") -> None:
        if downstream:
            self._peer(downstream[0]).call("open_block_stream", block_id,
                                           downstream[1:])
        with self._lock:
            self._uploads[block_id] = {"downstream": list(downstream),
                                       "ts": time.monotonic()}
        self.store.open_stream(block_id)

    def write_block_chunk(self, block_id: int, data: bytes) -> None:
        with self._lock:
            up = self._uploads.get(block_id)
        if up is None:
            raise KeyError(f"no open stream for block {block_id}")
        if up["downstream"]:
            self._peer(up["downstream"][0]).call("write_block_chunk",
                                                 block_id, data)
        self.store.append_stream(block_id, data)
        up["ts"] = time.monotonic()

    def commit_block_stream(self, block_id: int) -> None:
        with self._lock:
            up = self._uploads.pop(block_id, None)
        if up is None:
            raise KeyError(f"no open stream for block {block_id}")
        if up["downstream"]:
            self._peer(up["downstream"][0]).call("commit_block_stream",
                                                 block_id)
        t0 = time.monotonic()
        size = self.store.finalize_stream(block_id)
        self._write_bytes.observe(size)
        self._write_seconds.observe(time.monotonic() - t0)
        self.nn.call("block_received", self.addr, block_id, size)

    def abort_block_stream(self, block_id: int) -> None:
        with self._lock:
            up = self._uploads.pop(block_id, None)
        if up and up["downstream"]:
            try:
                self._peer(up["downstream"][0]).call("abort_block_stream",
                                                     block_id)
            except Exception:  # noqa: BLE001 — best-effort chain abort
                pass
        self.store.abort_stream(block_id)

    def block_checksum(self, block_id: int) -> int:
        return zlib.crc32(self.store.read(block_id))
