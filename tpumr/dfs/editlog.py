"""NameNode persistence: segmented edit-log journal + image checkpoints.

≈ ``FSEditLog`` (hdfs/server/namenode/FSEditLog.java, 1433 LoC — in
particular rollEditLog's edits/edits.new split), ``FSImage``
(FSImage.java, 1832 LoC) and the SecondaryNameNode merge
(SecondaryNameNode.java:64). Contracts kept:

- every namespace mutation is appended + fsynced to the journal BEFORE
  the in-memory change is visible to clients;
- startup = load newest image, replay edits in order;
- a checkpoint merges image+edits into a fresh image and purges exactly
  the merged edits.

The journal is a sequence of numbered segment files
(``edits-0000000001.jsonl`` …): the writer rolls to a new segment when the
current one passes ``segment_bytes`` (≈ FSEditLog roll), so a checkpoint
can seal-and-purge whole segments without ever truncating the file being
written. Sealed segments are deleted only AFTER the merged image is
durably in place (crash between a secondary's fetch and its upload loses
nothing — the reference's CheckpointSignature rollback guarantee).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Iterator

IMAGE_NAME = "fsimage.json"
#: legacy single-file journal name (still replayed first if present)
EDITS_NAME = "edits.jsonl"
_SEG_RE = re.compile(r"^edits-(\d{10})\.jsonl$")


def _segment_name(n: int) -> str:
    return f"edits-{n:010d}.jsonl"


def _tail_is_clean(path: str) -> bool:
    """True when the file is empty or its last line is a complete JSON
    record (ends with a newline and parses)."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if size == 0:
                return True
            f.seek(max(0, size - 65536))
            tail = f.read()
    except OSError:
        return False
    if not tail.endswith(b"\n"):
        return False
    last = tail.rstrip(b"\n").rsplit(b"\n", 1)[-1]
    if not last:
        return True
    try:
        json.loads(last)
        return True
    except json.JSONDecodeError:
        return False


def list_segments(name_dir: str) -> "list[str]":
    """Segment paths in write order (legacy single file first)."""
    out = []
    legacy = os.path.join(name_dir, EDITS_NAME)
    if os.path.exists(legacy):
        out.append(legacy)
    nums = []
    try:
        for name in os.listdir(name_dir):
            m = _SEG_RE.match(name)
            if m:
                nums.append(int(m.group(1)))
    except FileNotFoundError:
        pass
    out.extend(os.path.join(name_dir, _segment_name(n))
               for n in sorted(nums))
    return out


class FSEditLog:
    """Append-only JSON-line journal over numbered segments, durable
    (fsynced) before ``log`` returns, size-triggered rolls.

    GROUP COMMIT: concurrent ``log`` callers batch into one fsync.
    Appends are serialized under an internal mutex (append order is
    journal order); each caller then either becomes the sync LEADER —
    fsyncs once, covering every record appended so far — or, when a
    leader's fsync is already in flight, waits for a leader whose sync
    covers its record. With the namenode's striped locking many ops
    journal concurrently; batching turns N fsyncs at 1-5 ms each into
    ~1, which is the difference between the editlog being the
    mutation-throughput ceiling and it being noise. The WAL contract
    is unchanged: ``log`` returns only after ITS record is durable.
    ``records``/``syncs`` counters expose the achieved batching ratio.
    """

    def __init__(self, name_dir: str, segment_bytes: int = 0) -> None:
        self.name_dir = name_dir
        #: roll threshold; 0 = never auto-roll mid-write (rolls still
        #: happen at checkpoints)
        self.segment_bytes = segment_bytes
        os.makedirs(name_dir, exist_ok=True)
        existing = [p for p in list_segments(name_dir)
                    if not p.endswith(EDITS_NAME)]
        self._seg_no = (int(_SEG_RE.match(os.path.basename(existing[-1]))
                            .group(1)) if existing else 1)
        # never append to a segment with a torn tail (crash mid-write):
        # replay stops at the torn line, so bytes appended after it would
        # be silently skipped on the NEXT replay while later segments
        # still apply — seal it and write to a fresh segment instead
        if existing and not _tail_is_clean(existing[-1]):
            self._seg_no += 1
        self.path = os.path.join(name_dir, _segment_name(self._seg_no))
        self._f = open(self.path, "ab")
        # optional latency/size histograms (bind_metrics); None until the
        # owning NameNode wires a registry, so a bare FSNamesystem (tests,
        # offline tools) pays nothing
        self._append_hist: Any = None
        self._sync_hist: Any = None
        self._batch_hist: Any = None
        self._group_hist: Any = None
        # group-commit state, all under _cond's mutex: appends bump
        # _appended; a single leader fsyncs and advances _synced; the
        # _syncing flag is the leader baton
        self._cond = threading.Condition()
        self._appended = 0
        self._synced = 0
        self._syncing = False
        # highest seq whose durability is UNKNOWN because a leader's
        # fsync failed; waiters covered by it raise instead of acking
        self._failed = 0
        #: records appended / fsyncs issued — syncs << records under
        #: concurrency is group commit working
        self.records = 0
        self.syncs = 0

    def bind_metrics(self, append_hist: Any, sync_hist: Any,
                     batch_hist: Any,
                     group_hist: Any = None) -> "FSEditLog":
        """Attach append-latency / fsync-latency / record-size (and
        optionally records-per-fsync) histograms. The fsync is the
        WAL's durability point — its p99 is the floor under every
        namespace-mutation latency, which is why it gets its own series
        instead of hiding inside the append total."""
        self._append_hist = append_hist
        self._sync_hist = sync_hist
        self._batch_hist = batch_hist
        self._group_hist = group_hist
        return self

    def log(self, op: dict) -> None:
        t0 = time.monotonic()
        rec = json.dumps(op, separators=(",", ":")).encode() + b"\n"
        roll_now = False
        # The WAL contract REQUIRES this I/O under the caller's
        # namespace stripe lock: every mutation must be durable before
        # it is visible, so append + group-commit fsync are the one
        # sanctioned blocking region under those locks. The cost is
        # measured, not hidden: nn_editlog_sync_seconds is the floor
        # under nn_lock_hold_seconds{lock=namespace*}.
        with self._cond:
            self._f.write(rec)
            self._f.flush()
            self._appended += 1
            self.records += 1
            my_seq = self._appended
            while self._synced < my_seq:
                if self._failed >= my_seq:
                    # a leader's fsync covering our record failed: its
                    # durability is UNKNOWN (it sits in an abandoned
                    # segment and may or may not replay after a crash)
                    # — never tell the caller it committed
                    raise IOError(
                        f"editlog sync failed: durability unknown for "
                        f"record {my_seq} (synced {self._synced})")
                if self._syncing:
                    # a leader's fsync is in flight; if it began before
                    # our append it won't cover us — wait and re-check
                    self._cond.wait()  # tpulint: disable=lock-blocking
                    continue
                self._syncing = True
                upto = self._appended
                batch_n = upto - self._synced
                f = self._f
                self._cond.release()
                t1 = time.monotonic()
                ok = False
                try:
                    os.fsync(f.fileno())
                    ok = True
                finally:
                    t2 = time.monotonic()
                    self._cond.acquire()
                    self._syncing = False
                    if ok:
                        self._synced = max(self._synced, upto)
                        self.syncs += 1
                        if self._sync_hist is not None:
                            self._sync_hist.observe(t2 - t1)
                        if self._group_hist is not None:
                            self._group_hist.observe(float(batch_n))
                    else:
                        # fsyncgate: after a failed fsync the kernel may
                        # mark the dirty pages clean, so a FOLLOWER
                        # retrying fsync on this fd could be told success
                        # for records that were never made durable —
                        # poison every record on this fd and abandon the
                        # segment; our own exception propagates
                        self._sync_failed_locked()
                    self._cond.notify_all()
            if self.segment_bytes and self._f.tell() >= self.segment_bytes:
                roll_now = True
        if self._append_hist is not None:
            self._append_hist.observe(time.monotonic() - t0)
            self._batch_hist.observe(len(rec))
        if roll_now:
            self._maybe_roll()

    def _sync_failed_locked(self) -> None:
        """Leader-fsync failure handling, under ``_cond``: record the
        poisoned high-water seq and swap to a FRESH segment so later
        appends (and their leaders' fsyncs) run on an fd with no
        unsynced history. The abandoned segment keeps whatever the OS
        persisted — the poisoned records may replay after a crash even
        though their callers saw an error, the standard
        committed-but-unacked WAL ambiguity (docs/OPERATIONS.md)."""
        self._failed = max(self._failed, self._appended)
        try:
            self._f.close()
        except OSError:
            pass
        self._seg_no += 1
        self.path = os.path.join(self.name_dir,
                                 _segment_name(self._seg_no))
        try:
            self._f = open(self.path, "ab")  # tpulint: disable=lock-blocking
        except OSError:
            # journal is down hard; subsequent appends raise on the
            # closed file, which is the honest surface for that state
            pass

    def close(self) -> None:
        with self._cond:
            while self._syncing:
                self._cond.wait()
            self._f.close()

    def _maybe_roll(self) -> None:
        """Size-triggered roll; re-checks under the mutex so a burst of
        concurrent threshold-crossing appends rolls once, not N times."""
        with self._cond:
            if self.segment_bytes and self._f.tell() >= self.segment_bytes:
                self._roll_locked()

    def roll(self) -> "list[str]":
        """Seal the current segment and open the next (≈ rollEditLog:
        edits → edits.new). Returns every sealed segment path — the set a
        checkpoint may purge once its merged image is durable."""
        with self._cond:
            return self._roll_locked()

    def _roll_locked(self) -> "list[str]":
        while self._syncing:
            # never close the fd out from under an in-flight leader
            self._cond.wait()  # tpulint: disable=lock-blocking
        if self._synced < self._appended:
            # appended-but-unsynced records (their owners are queued on
            # the mutex to lead): seal durably covers them, and
            # advancing _synced releases those owners on wake
            try:
                os.fsync(self._f.fileno())
            except OSError:
                # same poisoning as a failed group-commit leader: wake
                # the queued owners so they raise instead of hanging
                self._sync_failed_locked()
                self._cond.notify_all()
                raise
            self.syncs += 1
            self._synced = self._appended
            self._cond.notify_all()
        self._f.close()
        sealed = list_segments(self.name_dir)
        self._seg_no += 1
        self.path = os.path.join(self.name_dir,
                                 _segment_name(self._seg_no))
        # see log(): the rare size-triggered roll's open() is part of
        # the sanctioned WAL blocking region under the namespace locks
        self._f = open(self.path, "ab")  # tpulint: disable=lock-blocking
        return sealed

    def total_bytes(self) -> int:
        """Journal size on disk — the auto-checkpoint trigger input."""
        total = 0
        for p in list_segments(self.name_dir):
            try:
                total += os.path.getsize(p)
            except OSError:
                pass
        return total

    @staticmethod
    def purge(paths: "list[str]") -> None:
        """Delete merged segments (checkpoint completion)."""
        for p in paths:
            try:
                os.remove(p)
            except FileNotFoundError:
                pass

    @staticmethod
    def replay(name_dir: str,
               paths: "list[str] | None" = None) -> Iterator[dict]:
        for path in (list_segments(name_dir) if paths is None else paths):
            if not os.path.exists(path):
                continue
            with open(path, "rb") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        # torn tail write from a crash: stop this segment
                        # at the last complete record (journal recovery)
                        break


class FSImage:
    """Namespace snapshot: {path: inode_dict} + block/generation counters."""

    @staticmethod
    def save(name_dir: str, namespace: dict, counters: dict) -> None:
        os.makedirs(name_dir, exist_ok=True)
        tmp = os.path.join(name_dir, IMAGE_NAME + ".tmp")
        with open(tmp, "w") as f:
            json.dump({"namespace": namespace, "counters": counters}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(name_dir, IMAGE_NAME))

    @staticmethod
    def load(name_dir: str) -> tuple[dict, dict]:
        path = os.path.join(name_dir, IMAGE_NAME)
        if not os.path.exists(path):
            return {}, {}
        with open(path) as f:
            data = json.load(f)
        return data.get("namespace", {}), data.get("counters", {})


def checkpoint(name_dir: str, apply_op: Any) -> None:
    """Merge image + all on-disk edits → new image, then purge exactly the
    merged segments (≈ the SecondaryNameNode doCheckpoint merge, done
    in-process). ``apply_op(namespace, counters, op)`` is the namesystem's
    replay function, shared with startup so merge and live replay never
    diverge. Caller must have closed/rolled the live writer first."""
    merged = list_segments(name_dir)
    namespace, counters = FSImage.load(name_dir)
    for op in FSEditLog.replay(name_dir, merged):
        apply_op(namespace, counters, op)
    FSImage.save(name_dir, namespace, counters)
    FSEditLog.purge(merged)
