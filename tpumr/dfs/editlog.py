"""NameNode persistence: edit log journal + image checkpoints.

≈ ``FSEditLog`` (hdfs/server/namenode/FSEditLog.java, 1433 LoC), ``FSImage``
(FSImage.java, 1832 LoC) and the SecondaryNameNode merge
(SecondaryNameNode.java:64). Contracts kept: every namespace mutation is
appended + fsynced to the journal BEFORE being applied in memory is visible
to clients; startup = load newest image, replay edits; a checkpoint merges
image+edits into a fresh image and truncates the journal (the secondary's
doCheckpoint cycle, here callable in-process or from the standalone
:class:`CheckpointNode`)."""

from __future__ import annotations

import json
import os
from typing import Any, Iterator

IMAGE_NAME = "fsimage.json"
EDITS_NAME = "edits.jsonl"


class FSEditLog:
    """Append-only JSON-line journal with fsync on every op."""

    def __init__(self, name_dir: str) -> None:
        self.path = os.path.join(name_dir, EDITS_NAME)
        os.makedirs(name_dir, exist_ok=True)
        self._f = open(self.path, "ab")

    def log(self, op: dict) -> None:
        self._f.write(json.dumps(op, separators=(",", ":")).encode() + b"\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        self._f.close()

    def roll(self) -> None:
        """Truncate after a checkpoint (≈ rollEditLog + purge)."""
        self._f.close()
        self._f = open(self.path, "wb")

    @staticmethod
    def replay(name_dir: str) -> Iterator[dict]:
        path = os.path.join(name_dir, EDITS_NAME)
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    # torn tail write from a crash: stop at the last
                    # complete record (journal recovery semantics)
                    return


class FSImage:
    """Namespace snapshot: {path: inode_dict} + block/generation counters."""

    @staticmethod
    def save(name_dir: str, namespace: dict, counters: dict) -> None:
        os.makedirs(name_dir, exist_ok=True)
        tmp = os.path.join(name_dir, IMAGE_NAME + ".tmp")
        with open(tmp, "w") as f:
            json.dump({"namespace": namespace, "counters": counters}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(name_dir, IMAGE_NAME))

    @staticmethod
    def load(name_dir: str) -> tuple[dict, dict]:
        path = os.path.join(name_dir, IMAGE_NAME)
        if not os.path.exists(path):
            return {}, {}
        with open(path) as f:
            data = json.load(f)
        return data.get("namespace", {}), data.get("counters", {})


def checkpoint(name_dir: str, apply_op: Any) -> None:
    """Merge image + edits → new image, truncate edits (≈ the
    SecondaryNameNode doCheckpoint merge). ``apply_op(namespace, counters,
    op)`` is the namesystem's replay function, shared with startup so the
    merge and live replay can never diverge."""
    namespace, counters = FSImage.load(name_dir)
    for op in FSEditLog.replay(name_dir):
        apply_op(namespace, counters, op)
    FSImage.save(name_dir, namespace, counters)
    with open(os.path.join(name_dir, EDITS_NAME), "wb"):
        pass
