"""NameNode — namespace + block management master.

≈ ``org.apache.hadoop.hdfs.server.namenode.{NameNode,FSNamesystem}``
(reference: FSNamesystem.java, 5907 LoC; NameNode.java RPC front). Contracts
reproduced:

- flat namespace of files/dirs; files are ordered block lists; every
  mutation journals to the edit log BEFORE applying (editlog.py);
- single-writer leases: create() grants the lease, concurrent creates fail
  (AlreadyBeingCreatedException semantics); expired leases are recovered by
  finalizing the file with its reported blocks (LeaseManager);
- block locations are NOT persisted — rebuilt from DataNode block reports
  (BlocksMap + processReport semantics);
- safemode on startup until a threshold fraction of known blocks have a
  reported replica (``dfs.safemode.threshold.pct``, FSNamesystem.SafeModeInfo);
- heartbeat-lease liveness for DataNodes; a dead DataNode's replicas go
  under-replicated and the replication monitor schedules re-replication on
  surviving nodes (heartbeatCheck + ReplicationMonitor → DNA_TRANSFER /
  DNA_INVALIDATE commands piggybacked on heartbeats);
- write-path placement excludes client-reported bad nodes (abandonBlock +
  excludedNodes on addBlock).
"""

from __future__ import annotations

import contextlib
import logging
import random
import threading
import time
from typing import Any

from tpumr.dfs.editlog import FSEditLog, FSImage
from tpumr.dfs.hotblocks import HotBlockTable
from tpumr.dfs.nslock import NamespaceLocks
from tpumr.ipc.rpc import RpcServer

#: ≈ ClientProtocol.versionID (hdfs/protocol/ClientProtocol.java)
PROTOCOL_VERSION = 61


class SafeModeError(RuntimeError):
    pass


class LeaseError(RuntimeError):
    pass


class QuotaExceededError(RuntimeError):
    pass


def _now() -> float:
    return time.time()


class FSNamesystem:
    """Namespace + block map + leases. All public mutators journal first."""

    def __init__(self, name_dir: str, conf: Any) -> None:
        self.conf = conf
        self.name_dir = name_dir
        # striped locking (nslock.py): path ops take only their
        # subtree's stripe, datanode/block ops take only the blocks
        # lock, and the global ``namespace`` lock is reserved for
        # cross-stripe structural work — wait/hold land in
        # nn_lock_*_seconds{lock=namespace|namespace-stripe|
        # namespace-blocks}; histograms bind later (bind_metrics)
        self.locks = NamespaceLocks(
            stripes=int(conf.get("tdfs.namenode.lock.stripes", 8)),
            depth=int(conf.get("tdfs.namenode.lock.stripe.depth", 2)))
        #: back-compat alias: the structural/global lock, still named
        #: "namespace" in the rank table and metric labels. Holding it
        #: alone does NOT exclude striped ops — quiesced-state readers
        #: (tests, status pages) are fine, mutators must go through
        #: _locked()/locks.structural()
        self.lock = self.locks.global_lock
        #: the block/datanode-plane lock — short sections, no journaling
        self._blk = self.locks.blocks
        #: leaf mutex for the quota usage cache (_quota_usage): charged
        #: from any stripe, so the per-entry += must not race; plain
        #: unranked Lock because nothing ever blocks under it
        self._quota_mu = threading.Lock()
        self.default_replication = int(conf.get("dfs.replication", 3))
        self.default_block_size = int(conf.get("dfs.block.size",
                                               8 * 1024 * 1024))
        self.safemode_threshold = float(conf.get("dfs.safemode.threshold.pct",
                                                 0.999))
        self.lease_hard_limit = float(conf.get("tdfs.lease.hard.limit.s", 60))

        # persisted state: namespace + counters (image ∪ edits)
        self.namespace, self.counters = FSImage.load(name_dir)
        for op in FSEditLog.replay(name_dir):
            self.apply_op(self.namespace, self.counters, op)
        self.counters.setdefault("next_block", 1)
        self.counters.setdefault("gen", 1)
        self._edits_segment_bytes = int(
            float(conf.get("tdfs.edits.segment.mb", 16)) * 1024 * 1024)
        self.edits = FSEditLog(name_dir,
                               segment_bytes=self._edits_segment_bytes)
        #: sealed segments shipped to a secondary, purged on put_image
        self._checkpoint_segments: list[str] = []
        #: checkpoint epoch token (≈ CheckpointSignature): bumped by every
        #: get_name_state fetch AND every in-process checkpoint; put_image
        #: must echo the token of the LATEST fetch or it is refused — a
        #: stale secondary upload can never purge segments its merged
        #: image does not cover
        self._ckpt_token = 0
        #: serializes the checkpoint flows (save_namespace /
        #: get_name_state / put_image) against each other so their
        #: image + sealed-segment file I/O can run OUTSIDE the namespace
        #: lock: the token protocol already refuses cross-process
        #: staleness; this mutex removes the in-process interleavings
        #: (two concurrent checkpoints double-applying sealed segments).
        #: Always acquired BEFORE self.lock, never while holding it.
        self._ckpt_mu = threading.Lock()

        # permission model ≈ FSNamesystem/FSPermissionChecker: owner/group/
        # mode per inode; the NN process user is the superuser; identity is
        # the (signed) simple-auth user asserted on each RPC. In-process
        # calls (monitor threads, lease recovery) carry no RPC user and
        # bypass checks — they ARE the namesystem.
        self.permissions_enabled = conf.get_boolean("dfs.permissions", True)
        import getpass
        self.superuser = str(conf.get("tdfs.superuser", "")
                             or getpass.getuser())
        self.supergroup = str(conf.get("dfs.permissions.supergroup",
                                       "supergroup"))
        # root inode: superuser-owned 0755 like a formatted HDFS
        # namespace. JOURNALED like any mkdir (the "format" record) —
        # an un-journaled root would be re-stamped with a fresh mtime
        # by every restart that replays from a checkpoint image, so the
        # namespace would never be byte-identical across a crash
        if "/" not in self.namespace:
            op = {"op": "mkdir", "path": "/", "t": _now(),
                  "o": self.superuser, "g": self.supergroup, "m": 0o755}
            self.edits.log(op)
            self.apply_op(self.namespace, self.counters, op)
        root = self.namespace["/"]
        root.setdefault("owner", self.superuser)
        root.setdefault("group", self.supergroup)
        root.setdefault("mode", 0o755)
        #: corrupt replicas reported by clients: bid -> {addr}
        self.corrupt_replicas: dict[int, set[str]] = {}
        #: reverse index bid -> owning path, kept alongside the other
        #: volatile block maps — report_bad_block's permission lookup must
        #: not scan the namespace under the lock
        self.block_to_path: dict[int, str] = {
            b[0]: p for p, ino in self.namespace.items()
            if ino.get("type") == "file" for b in ino.get("blocks", [])}
        #: addr -> "decommissioning" | "decommissioned" (admin-driven,
        #: ≈ the exclude-file + refreshNodes workflow). Journaled through
        #: 'decommission' ops into counters so an NN restart cannot
        #: silently return a draining node to service.
        self.decommissioning: dict[str, str] = \
            self.counters.setdefault("decommissioning", {})
        # volatile state, rebuilt at runtime
        self.block_locations: dict[int, set[str]] = {}   # bid -> {dn addr}
        self.block_sizes: dict[int, int] = {}            # reported sizes
        self.datanodes: dict[str, dict] = {}             # addr -> info
        self.commands: dict[str, list[dict]] = {}        # addr -> pending
        self.leases: dict[str, dict] = {}                # client -> lease

        #: incremental per-quota-dir usage cache: qpath -> [inodes, bytes]
        #: (≈ INodeDirectoryWithQuota's cached counts) — quota checks must
        #: not rescan the namespace under the lock on every write.
        #: Maintained by the mutators via _charge, re-derived at every
        #: checkpoint (self-healing against conservative drift from
        #: lease-recovery closes). Needs block_sizes initialized above.
        self._quota_usage: dict[str, list] = {}
        self._rebuild_quota_usage()

        # The safemode denominator counts only CLOSED files' blocks —
        # matching the live accounting, where blocks enter
        # total_known_blocks at complete/close. A file open at the
        # crash may hold a journaled add_block the writer never pushed
        # to any DataNode; counting it would hold _reported_fraction
        # below threshold FOREVER (no replica exists to report).
        # HDFS likewise excludes under-construction blocks from
        # SafeModeInfo's blockTotal.
        self.total_known_blocks = sum(
            len(i.get("blocks", [])) for i in self.namespace.values()
            if i.get("type") == "file" and not i.get("uc"))
        self.safemode = self.total_known_blocks > 0
        # none of a restart-survivor uc file's blocks are in the
        # denominator, so the eventual close/lease-recovery delta adds
        # ALL of them (len(blocks) - 0) — same contract as create,
        # where post-open blocks wait for complete to be counted
        self._uc_counted: dict[str, int] = {
            p: 0 for p, i in self.namespace.items()
            if i.get("type") == "file" and i.get("uc")}

        # rack awareness ≈ FSNamesystem's clusterMap (NetworkTopology)
        from tpumr.net import NetworkTopology, resolver_from_conf
        self.topology = NetworkTopology(resolver_from_conf(conf))

        #: cluster-wide hot-block view folded from the bounded
        #: SpaceSaving slices datanodes piggyback on heartbeats
        #: (hotblocks.py) — served at /hotblocks + get_hot_blocks
        self.hot_blocks = HotBlockTable(
            k=int(conf.get("tpumr.dn.hotblocks.k", 64)))
        # hot-block auto-replication policy (hotblock_check): when one
        # block draws more than `share` of cluster reads, raise its
        # replica target toward the cap; the boost decays back once the
        # block cools (the DN sketches decay too, so share follows the
        # CURRENT mix, not history)
        self.hot_share = float(conf.get("tdfs.hotblocks.replicate.share",
                                        0.3))
        self.hot_min_reads = int(conf.get(
            "tdfs.hotblocks.replicate.min.reads", 200))
        self.hot_cap = int(conf.get("tdfs.hotblocks.replicate.cap", 4))
        self.hot_cool_s = float(conf.get("tdfs.hotblocks.cool.s", 15.0))
        #: bid -> {"boost": target_replicas, "hot_mono": last_hot_ts} —
        #: consulted by replication_check, guarded by self._blk
        self.hot_boost: dict[int, dict] = {}

        # audit log ≈ FSNamesystem.logAuditEvent: one line per namespace
        # mutation on the dedicated "tpumr.nn.audit" logger, rate-capped
        # per second so a create storm cannot turn the audit trail into
        # the bottleneck it documents (suppressions are counted, never
        # silent)
        self._audit_enabled = conf.get_boolean("tpumr.nn.audit.enabled",
                                               False)
        self._audit_rate = int(conf.get("tpumr.nn.audit.rate.limit", 200))
        self._audit_log = logging.getLogger("tpumr.nn.audit")
        self._audit_window = -1
        self._audit_in_window = 0
        self.audit_emitted = 0
        self.audit_suppressed = 0

    # ------------------------------------------------------------ journal

    @staticmethod
    def apply_op(namespace: dict, counters: dict, op: dict) -> None:
        """Replay one journaled op onto a bare namespace. Shared by startup
        replay and checkpoint merge (editlog.checkpoint)."""
        kind = op["op"]
        p = op.get("path")
        if kind == "mkdir":
            namespace[p] = {"type": "dir", "mtime": op["t"],
                            "owner": op.get("o", ""),
                            "group": op.get("g", ""),
                            "mode": op.get("m", 0o755)}
        elif kind == "create":
            namespace[p] = {"type": "file", "blocks": [], "uc": True,
                            "replication": op["r"], "block_size": op["bs"],
                            "mtime": op["t"], "client": op.get("c", ""),
                            "owner": op.get("o", ""),
                            "group": op.get("g", ""),
                            "mode": op.get("m", 0o644)}
        elif kind == "append_open":
            namespace[p]["uc"] = True
            namespace[p]["client"] = op.get("c", "")
        elif kind == "add_block":
            namespace[p]["blocks"].append([op["bid"], 0])
        elif kind == "block_size":
            for b in namespace[p]["blocks"]:
                if b[0] == op["bid"]:
                    b[1] = op["size"]
        elif kind == "abandon":
            if p in namespace:  # tolerate journals from older builds
                namespace[p]["blocks"] = [b for b in namespace[p]["blocks"]
                                          if b[0] != op["bid"]]
        elif kind == "close":
            inode = namespace[p]
            inode["uc"] = False
            inode.pop("client", None)
            if "sizes" in op:
                for b in inode["blocks"]:
                    b[1] = op["sizes"].get(str(b[0]), b[1])
        elif kind == "rename":
            dst = op["dst"]
            moved = [(k, v) for k, v in namespace.items()
                     if k == p or k.startswith(p.rstrip("/") + "/")]
            for k, v in moved:
                del namespace[k]
                namespace[dst + k[len(p):]] = v
        elif kind == "delete":
            for k in [k for k in namespace
                      if k == p or k.startswith(p.rstrip("/") + "/")]:
                del namespace[k]
        elif kind == "set_repl":
            namespace[p]["replication"] = op["r"]
        elif kind == "chmod":
            namespace[p]["mode"] = op["m"]
        elif kind == "chown":
            if op.get("o"):
                namespace[p]["owner"] = op["o"]
            if op.get("g"):
                namespace[p]["group"] = op["g"]
        elif kind == "set_quota":
            ino = namespace[p]
            for field_name, key in (("ns_quota", "nsq"), ("sp_quota", "spq")):
                if key in op:
                    if op[key] is None or op[key] < 0:
                        ino.pop(field_name, None)
                    else:
                        ino[field_name] = op[key]
        elif kind == "decommission":
            d = counters.setdefault("decommissioning", {})
            if op.get("state"):
                d[op["addr"]] = op["state"]
            else:
                d.pop(op["addr"], None)
        elif kind == "counters":
            # allocator counters apply as a MONOTONIC max: with striped
            # locking two add_blocks in different stripes may journal
            # their counter bumps out of allocation order, and replaying
            # the smaller value last would re-issue a block id
            for k, v in op["values"].items():
                if k in ("next_block", "gen") and isinstance(v, int):
                    old = counters.get(k)
                    counters[k] = max(old, v) \
                        if isinstance(old, int) else v
                else:
                    counters[k] = v

    def _log(self, op: dict) -> None:
        self.edits.log(op)

    def _audit(self, cmd: str, src: str, dst: "str | None" = None,
               perm: "str | None" = None) -> None:
        """HDFS-style audit line (``ugi= ip= cmd= src= dst= perm=``) for
        one SUCCESSFUL namespace mutation — called after the journal
        append, so an audited op is always a durable op."""
        if not self._audit_enabled:
            return
        window = int(time.monotonic())
        if window != self._audit_window:
            self._audit_window = window
            self._audit_in_window = 0
        self._audit_in_window += 1
        if self._audit_rate and self._audit_in_window > self._audit_rate:
            self.audit_suppressed += 1
            return
        self.audit_emitted += 1
        self._audit_log.info(
            "ugi=%s ip=- cmd=%s src=%s dst=%s perm=%s",
            self._caller() or self.superuser, cmd, src,
            "-" if dst is None else dst, "-" if perm is None else perm)

    def bind_metrics(self, reg: Any) -> None:
        """Attach the namespace-lock wait/hold and editlog histograms —
        the lock and journal exist before the metrics registry does, so
        they late-bind exactly like the master's lock classes."""
        from tpumr.metrics.histogram import BYTES
        self.locks.bind_metrics(reg)
        self.edits.bind_metrics(
            reg.histogram("nn_editlog_append_seconds"),
            reg.histogram("nn_editlog_sync_seconds"),
            reg.histogram("nn_editlog_batch_bytes", bounds=BYTES),
            reg.histogram("nn_editlog_group_ops"))

    # ------------------------------------------------------------ helpers

    def _locked(self, *paths: str, ensure: "str | None" = None):
        """Lock context for an op on ``paths``: their stripes in index
        order, or structural when any path is too shallow to stripe.
        ``ensure``: the op will _ensure_parents this path — when a
        MISSING ancestor is itself too shallow to stripe (a new
        top-level dir), creating it is structural work, decided here
        with lock-free point reads before anything is acquired."""
        if ensure is not None:
            p = self._parent_of(ensure)
            while p != "/" and p not in self.namespace:
                if self.locks.stripe_index(p) is None:
                    return self.locks.structural()
                p = self._parent_of(p)
        return self.locks.for_paths(*paths)

    def _ns_items(self) -> "list[tuple[str, dict]]":
        """Point-in-time snapshot of the namespace dict for full scans
        that don't hold a lock excluding all mutators (blocks-plane
        sweeps, status pages). ``list(dict.items())`` is GIL-atomic in
        CPython — same contract lock_table() relies on — so a scan can
        never see a resize mid-iteration; individual inode dicts may
        still be mutated concurrently, which these scans tolerate
        (point-in-time staleness, never corruption)."""
        return list(self.namespace.items())

    def _check_safemode(self) -> None:
        if self.safemode:
            raise SafeModeError(
                "NameNode is in safe mode: "
                f"{self._reported_fraction():.3f} of "
                f"{self.total_known_blocks} blocks reported "
                f"(threshold {self.safemode_threshold})")

    def _reported_fraction(self) -> float:
        if self.total_known_blocks == 0:
            return 1.0
        # uc files mirror the denominator: their blocks are not in
        # total_known_blocks until close, so counting their reported
        # replicas here could push the fraction past threshold while
        # CLOSED files' blocks are still dark
        reported = sum(1 for _, i in self._ns_items()
                       if i.get("type") == "file" and not i.get("uc")
                       for b in i.get("blocks", [])
                       if self.block_locations.get(b[0]))
        return reported / self.total_known_blocks

    def _maybe_leave_safemode(self) -> None:
        if self.safemode and \
                self._reported_fraction() >= self.safemode_threshold:
            self.safemode = False

    def _ensure_parents(self, path: str,
                        user: "str | None" = None) -> None:
        parts = [p for p in path.split("/") if p]
        cur = ""
        for part in parts[:-1]:
            cur += "/" + part
            inode = self.namespace.get(cur)
            if inode is None:
                if not self.locks.covers(cur):
                    # striped context, missing ancestor OUTSIDE the held
                    # stripes: _locked()'s pre-check saw it present, so
                    # a structural delete won the race since — fail like
                    # any create under a just-deleted tree (a retry
                    # re-runs the pre-check and escalates)
                    raise FileNotFoundError(
                        f"{cur} (parent deleted concurrently)")
                op = {"op": "mkdir", "path": cur, "t": _now(),
                      "o": user or self.superuser, "g": self.supergroup,
                      "m": 0o755}
                self._log(op)
                self.apply_op(self.namespace, self.counters, op)
                self._charge(cur, 1, 0)
            elif inode["type"] != "dir":
                raise NotADirectoryError(cur)

    def _inode(self, path: str) -> dict:
        inode = self.namespace.get(path)
        if inode is None:
            raise FileNotFoundError(path)
        return inode

    # ------------------------------------------------------------ permissions

    @staticmethod
    def _caller() -> "str | None":
        from tpumr.ipc.rpc import current_rpc_user
        return current_rpc_user()

    def _groups_of(self, user: str) -> set:
        """Static group mapping from conf (``tpumr.user.groups.<user>`` =
        comma list) ≈ the reference's configurable GroupMappingServiceProvider
        — no JNI/shell group lookup on the NameNode's hot path."""
        gs = self.conf.get(f"tpumr.user.groups.{user}")
        return {s.strip() for s in str(gs).split(",")} if gs else set()

    @staticmethod
    def _parent_of(path: str) -> str:
        return path.rstrip("/").rsplit("/", 1)[0] or "/"

    def _check_access(self, path: str, want: int,
                      user: "str | None") -> None:
        """rwx bit check (want: 4=r, 2=w, 1=x) ≈ FSPermissionChecker.check.
        None user = in-process caller (the namesystem itself); superuser
        bypasses everything."""
        if (not self.permissions_enabled or user is None
                or user == self.superuser):
            return
        inode = self.namespace.get(path)
        if inode is None:
            return
        # same defaults get_status displays — enforcement and ls must
        # never disagree about what a missing mode means
        mode = inode.get("mode",
                         0o755 if inode.get("type") == "dir" else 0o644)
        owner = inode.get("owner", "")
        group = inode.get("group", "")
        # pre-permission inodes (replayed from old journals) have no
        # owner: everyone gets the owner bits — an upgrade must not lock
        # users out of trees they created before permissions existed
        if user == owner or owner == "":
            ok = (mode >> 6) & want
        elif group and group in self._groups_of(user):
            ok = (mode >> 3) & want
        else:
            ok = mode & want
        if not ok:
            access = {4: "READ", 2: "WRITE", 1: "EXECUTE"}.get(want, want)
            raise PermissionError(
                f"Permission denied: user={user}, access={access}, "
                f"inode={path} (owner={owner or '?'}, "
                f"mode={oct(mode & 0o777)})")

    def _check_parent_write(self, path: str, user: "str | None") -> None:
        """WRITE on the nearest EXISTING ancestor dir — creating a deep
        path checks where the subtree attaches, like the reference's
        checkAncestorAccess."""
        p = self._parent_of(path)
        while p != "/" and p not in self.namespace:
            p = self._parent_of(p)
        self._check_access(p, 2, user)

    def _check_superuser(self, what: str) -> None:
        user = self._caller()
        if (self.permissions_enabled and user is not None
                and user != self.superuser):
            raise PermissionError(
                f"Permission denied: only the superuser may {what}")

    # ------------------------------------------------------------ quotas

    def _quota_ancestors(self, path: str) -> "list[tuple[str, dict]]":
        """Ancestor dirs of ``path`` (inclusive) carrying a quota."""
        out = []
        p = path
        while True:
            ino = self.namespace.get(p)
            if ino is not None and ("ns_quota" in ino or "sp_quota" in ino):
                out.append((p, ino))
            if p == "/":
                return out
            p = self._parent_of(p)

    def _subtree_usage(self, root: str) -> "tuple[int, int]":
        """(inode_count, consumed_bytes) under ``root`` — consumed =
        block bytes × replication, the reference's diskspace accounting
        (INodeDirectoryWithQuota). Computed on demand: quota dirs are
        rare and ops on them tolerate the walk."""
        prefix = "/" if root == "/" else root.rstrip("/") + "/"
        inodes = 0
        consumed = 0
        for p, ino in self._ns_items():
            if p == root or p == "/" or not p.startswith(prefix):
                continue
            inodes += 1
            if ino.get("type") == "file":
                repl = ino.get("replication", 1)
                consumed += sum(self.block_sizes.get(b[0], b[1])
                                for b in ino.get("blocks", [])) * repl
        return inodes, consumed

    def _missing_ancestors(self, path: str) -> int:
        """How many intermediate dirs _ensure_parents would create —
        they count against namespace quotas too (the reference charges
        every new INode, not just the leaf)."""
        n = 0
        p = self._parent_of(path)
        while p != "/" and p not in self.namespace:
            n += 1
            p = self._parent_of(p)
        return n

    def _rebuild_quota_usage(self) -> None:
        """One scan re-deriving every quota dir's cached counters."""
        usage: dict[str, list] = {}
        for p, ino in self._ns_items():
            if ino.get("type") == "dir" and ("ns_quota" in ino
                                             or "sp_quota" in ino):
                usage[p] = None
        for q in usage:
            usage[q] = list(self._subtree_usage(q))
        self._quota_usage = usage

    def _charge(self, path: str, d_inodes: int, d_bytes: int) -> None:
        """Apply a usage delta at ``path`` to every quota-carrying PROPER
        ancestor's cached counters. No-op when no quotas exist. A quota
        dir's counters may be charged from ANY stripe (ancestors are
        not covered by the op's stripe set), hence the leaf mutex."""
        if not self._quota_usage:
            return
        with self._quota_mu:
            p = self._parent_of(path)
            while True:
                u = self._quota_usage.get(p)
                if u is not None:
                    u[0] += d_inodes
                    u[1] += d_bytes
                if p == "/":
                    return
                p = self._parent_of(p)

    def _check_quota(self, path: str, new_inodes: int,
                     new_bytes: int,
                     skip_ancestors_of: "str | None" = None) -> None:
        """≈ FSDirectory.verifyQuota: adding ``new_inodes`` namespace
        entries / ``new_bytes`` replicated bytes at ``path`` must fit
        every quota-carrying ancestor. ``skip_ancestors_of``: for renames,
        quota dirs that ALREADY contain the source subtree are exempt
        (the usage moves within them, net zero)."""
        skip = {q for q, _ in self._quota_ancestors(skip_ancestors_of)} \
            if skip_ancestors_of is not None else set()
        for qpath, ino in self._quota_ancestors(path):
            if qpath in skip:
                continue
            ns_q = ino.get("ns_quota")
            sp_q = ino.get("sp_quota")
            if ns_q is None and sp_q is None:
                continue
            cached = self._quota_usage.get(qpath)
            inodes, consumed = cached if cached is not None \
                else self._subtree_usage(qpath)
            if ns_q is not None and new_inodes \
                    and inodes + new_inodes > ns_q:
                raise QuotaExceededError(
                    f"namespace quota of {qpath} exceeded: "
                    f"quota={ns_q}, count={inodes + new_inodes}")
            if sp_q is not None and new_bytes \
                    and consumed + new_bytes > sp_q:
                raise QuotaExceededError(
                    f"space quota of {qpath} exceeded: quota={sp_q} B, "
                    f"consumed={consumed} B, requested={new_bytes} B")

    def set_quota(self, path: str, ns_quota: "int | None" = None,
                  sp_quota: "int | None" = None) -> None:
        """≈ ClientProtocol.setQuota (dfsadmin -setQuota/-setSpaceQuota):
        superuser only; None leaves a dimension unchanged, -1 clears it."""
        with self._locked(path):
            self._check_safemode()
            self._check_superuser("set quotas")
            inode = self._inode(path)
            if inode["type"] != "dir":
                raise NotADirectoryError(f"quotas apply to dirs: {path}")
            op: dict = {"op": "set_quota", "path": path}
            if ns_quota is not None:
                op["nsq"] = None if ns_quota < 0 else int(ns_quota)
            if sp_quota is not None:
                op["spq"] = None if sp_quota < 0 else int(sp_quota)
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            self._audit("setQuota", path)
            if "ns_quota" in inode or "sp_quota" in inode:
                # (re)derive this dir's counters at admin time — the one
                # place a full subtree scan is acceptable
                usage = list(self._subtree_usage(path))
                with self._quota_mu:
                    self._quota_usage[path] = usage
            else:
                with self._quota_mu:
                    self._quota_usage.pop(path, None)

    # ------------------------------------------------------------ client ops

    def create(self, path: str, client: str, replication: int | None,
               block_size: int | None, overwrite: bool) -> dict:
        with self._locked(path, ensure=path):
            self._check_safemode()
            user = self._caller()
            existing = self.namespace.get(path)
            if existing is not None:
                if existing["type"] == "dir":
                    raise IsADirectoryError(path)
                if existing.get("uc"):
                    raise LeaseError(
                        f"{path} already being created by "
                        f"{existing.get('client')}")
                if not overwrite:
                    raise FileExistsError(path)
                # overwrite is a truncate, not an unlink: WRITE on the
                # file itself suffices (HDFS startFile semantics) — the
                # internal delete must not re-check the parent dir
                self._check_access(path, 2, user)
                self._delete_impl(path, recursive=True)
            else:
                # a NEW namespace entry needs write on the parent
                self._check_parent_write(path, user)
                self._check_quota(
                    path, new_inodes=1 + self._missing_ancestors(path),
                    new_bytes=0)
            self._ensure_parents(path, user)
            r = replication or self.default_replication
            bs = block_size or self.default_block_size
            op = {"op": "create", "path": path, "r": r, "bs": bs,
                  "t": _now(), "c": client,
                  "o": user or self.superuser, "g": self.supergroup,
                  "m": 0o644}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            self._charge(path, 1, 0)
            with self._blk:
                lease = self.leases.setdefault(
                    client, {"paths": set(), "renewed": _now()})
                lease["paths"].add(path)
                # wall-clock "renewed" stays for the report surface;
                # expiry (lease_check) compares the monotonic twin so an
                # NTP step can neither mass-expire nor immortalize
                lease["renewed"] = _now()
                lease["renewed_mono"] = time.monotonic()
            self._audit("create", path)
            return {"replication": r, "block_size": bs}

    def append(self, path: str, client: str) -> dict:
        """Reopen a complete file for writing (≈ ClientProtocol.append,
        hdfs/DFSClient.java append path). BLOCK-GRANULAR by design:
        appended data lands in NEW blocks (short tail blocks stay
        short) — the reference appends into the last block under a new
        generation stamp; immutable whole-block datanode storage here
        makes new-blocks the honest equivalent (divergence documented in
        docs/OPERATIONS.md)."""
        with self._locked(path):
            self._check_safemode()
            user = self._caller()
            inode = self._inode(path)
            if inode["type"] != "file":
                raise IsADirectoryError(path)
            if inode.get("uc"):
                raise LeaseError(
                    f"{path} already open for writing by "
                    f"{inode.get('client')}")
            self._check_access(path, 2, user)
            op = {"op": "append_open", "path": path, "c": client,
                  "t": _now()}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            with self._blk:
                # pre-existing blocks are already in total_known_blocks
                self._uc_counted[path] = len(inode.get("blocks", []))
                lease = self.leases.setdefault(
                    client, {"paths": set(), "renewed": _now()})
                lease["paths"].add(path)
                lease["renewed"] = _now()
                lease["renewed_mono"] = time.monotonic()
            self._audit("append", path)
            return {"block_size": inode["block_size"],
                    "replication": inode.get("replication", 1)}

    def fsync(self, path: str, client: str, last_block_size: int) -> None:
        """Publish the last block's true size while the file stays open
        (≈ ClientProtocol.fsync — the hflush visibility point: readers
        see everything up to the last fsync'd block, never the writer's
        unflushed buffer)."""
        with self._locked(path):
            inode = self._inode(path)
            if not inode.get("uc") or inode.get("client") != client:
                raise LeaseError(
                    f"{client} does not hold the lease on {path}")
            if inode["blocks"] and last_block_size >= 0:
                bid = inode["blocks"][-1][0]
                op = {"op": "block_size", "path": path, "bid": bid,
                      "size": last_block_size}
                self._log(op)
                self.apply_op(self.namespace, self.counters, op)
                # settle the optimistic full-block charge now; the
                # client resets its prev-size so add_block/close never
                # re-settle the same block
                self._charge(path, 0,
                             (last_block_size - inode["block_size"])
                             * inode.get("replication", 1))

    def add_block(self, path: str, client: str,
                  prev_block_size: int = -1,
                  excluded: list[str] | None = None) -> dict:
        with self._locked(path):
            self._check_safemode()
            inode = self._inode(path)
            if not inode.get("uc") or inode.get("client") != client:
                raise LeaseError(f"{client} does not hold the lease on {path}")
            if inode["blocks"] and prev_block_size >= 0:
                bid = inode["blocks"][-1][0]
                op = {"op": "block_size", "path": path, "bid": bid,
                      "size": prev_block_size}
                self._log(op)
                self.apply_op(self.namespace, self.counters, op)
                # the previous block was charged a FULL block up front;
                # its real size is now known — settle the difference
                self._charge(path, 0,
                             (prev_block_size - inode["block_size"])
                             * inode.get("replication", 1))
            # space quota: a new block may consume up to block_size ×
            # replication (verifyQuota charges the full block up front)
            self._check_quota(path, new_inodes=0,
                              new_bytes=inode["block_size"]
                              * inode.get("replication", 1))
            with self._blk:
                # id allocation under the blocks lock (any stripe may
                # allocate); journal order may differ from allocation
                # order across stripes — apply_op's monotonic-max on
                # these counters makes replay order-independent
                bid = self.counters["next_block"]
                gen = self.counters["gen"]
                self.counters["next_block"] = bid + 1
                targets = self._choose_targets(inode["replication"],
                                               set(excluded or []))
            self._log({"op": "counters", "values":
                       {"next_block": bid + 1, "gen": gen}})
            if not targets:
                raise IOError("no DataNodes available for replication")
            op = {"op": "add_block", "path": path, "bid": bid}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            self._charge(path, 0,
                         inode["block_size"] * inode.get("replication", 1))
            with self._blk:
                self.block_to_path[bid] = path
            return {"block_id": bid, "gen": gen, "targets": targets}

    def abandon_block(self, path: str, client: str, block_id: int) -> None:
        """Client hit a pipeline failure: drop the block and let it retry
        (≈ ClientProtocol.abandonBlock). Validated BEFORE journaling — a
        bad op must never reach the edit log (replay has no error
        handling by design: a journaled op is a committed fact), and only
        the lease holder of an under-construction file may abandon, else
        any client could strip blocks from closed files."""
        with self._locked(path):
            inode = self.namespace.get(path)
            if inode is None or inode.get("type") != "file":
                raise FileNotFoundError(path)
            if not inode.get("uc") or inode.get("client") != client:
                raise LeaseError(
                    f"{client} does not hold the lease on {path}")
            if not any(b[0] == block_id for b in inode.get("blocks", [])):
                return  # retried abandon: already gone, nothing to charge
            op = {"op": "abandon", "path": path, "bid": block_id}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            self._charge(path, 0, -inode["block_size"]
                         * inode.get("replication", 1))
            with self._blk:
                self.block_to_path.pop(block_id, None)

    def complete(self, path: str, client: str, last_block_size: int) -> None:
        with self._locked(path):
            inode = self._inode(path)
            if not inode.get("uc") or inode.get("client") != client:
                raise LeaseError(f"{client} does not hold the lease on {path}")
            sizes = {}
            if inode["blocks"] and last_block_size >= 0:
                sizes[str(inode["blocks"][-1][0])] = last_block_size
            op = {"op": "close", "path": path, "sizes": sizes}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            self._audit("completeFile", path)
            if sizes:  # settle the last block's optimistic full charge
                self._charge(path, 0,
                             (last_block_size - inode["block_size"])
                             * inode.get("replication", 1))
            with self._blk:
                self.total_known_blocks += (len(inode["blocks"])
                                            - self._uc_counted.pop(path, 0))
                lease = self.leases.get(client)
                if lease:
                    lease["paths"].discard(path)

    def renew_lease(self, client: str) -> None:
        with self._blk:
            lease = self.leases.get(client)
            if lease:
                lease["renewed"] = _now()
                lease["renewed_mono"] = time.monotonic()

    def get_block_locations(self, path: str) -> list[dict]:
        with self._locked(path):
            inode = self._inode(path)
            if inode["type"] != "file":
                raise IsADirectoryError(path)
            self._check_access(path, 4, self._caller())
            out = []
            with self._blk:
                for bid, size in inode["blocks"]:
                    # shuffled, not sorted: with hot-block auto-replication
                    # adding replicas, clients that all read locations[0]
                    # would keep hammering one datanode — randomizing the
                    # order spreads a hot block's reads across its replicas
                    locs = list(self.block_locations.get(bid, ()))
                    random.shuffle(locs)
                    out.append({"block_id": bid,
                                "size": self.block_sizes.get(bid, size),
                                "locations": locs})
            return out

    # ------------------------------------------------------------ namespace

    def mkdirs(self, path: str) -> bool:
        with self._locked(path, ensure=path):
            self._check_safemode()
            if path in self.namespace:
                return self.namespace[path]["type"] == "dir"
            user = self._caller()
            self._check_parent_write(path, user)
            self._check_quota(
                path, new_inodes=1 + self._missing_ancestors(path),
                new_bytes=0)
            # parents only — creating the target through _ensure_parents
            # AND the op below would double-charge its quota inode
            self._ensure_parents(path, user)
            op = {"op": "mkdir", "path": path, "t": _now(),
                  "o": user or self.superuser, "g": self.supergroup,
                  "m": 0o755}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            self._charge(path, 1, 0)
            self._audit("mkdirs", path)
            return True

    def delete(self, path: str, recursive: bool = True) -> bool:
        # _locked(path) covers the whole subtree: every descendant of a
        # deep-enough path shares its stripe (see nslock.py)
        with self._locked(path):
            self._check_safemode()
            if path not in self.namespace:
                return False
            self._check_access(self._parent_of(path), 2, self._caller())
            out = self._delete_impl(path, recursive)
            if out:
                self._audit("delete", path)
            return out

    def _delete_impl(self, path: str, recursive: bool) -> bool:
        """Delete body, no permission check — for callers that already
        authorized the operation (create-with-overwrite checks WRITE on
        the file; re-checking the parent here would wrongly deny an
        owner overwriting their own file in a read-only dir)."""
        inode = self.namespace.get(path)
        if inode is None:
            return False
        children = [k for k in list(self.namespace)
                    if k.startswith(path.rstrip("/") + "/")]
        if inode["type"] == "dir" and children and not recursive:
            raise OSError(f"{path} is a non-empty directory")
        # schedule replica invalidation on the owning DataNodes; tally
        # the removed usage for the quota counters in the same pass
        doomed: list[int] = []
        removed_bytes = 0
        counted_removed = 0
        with self._blk:
            for k in children + [path]:
                node = self.namespace.get(k, {})
                if node.get("type") == "file":
                    blocks = node.get("blocks", [])
                    doomed.extend(b[0] for b in blocks)
                    repl = node.get("replication", 1)
                    # only blocks actually IN total_known_blocks leave it:
                    # a uc file's post-open blocks were never added (its
                    # pre-open count lives in _uc_counted), so decrementing
                    # per doomed block would drift the safemode denominator
                    counted_removed += (self._uc_counted.pop(k, 0)
                                        if node.get("uc") else len(blocks))
                    if node.get("uc") and blocks:
                        # the in-flight last block was charged a FULL block
                        # at add_block and never settled — refund what was
                        # charged, not its (still-zero) recorded size, or
                        # the phantom charge outlives the file
                        removed_bytes += (
                            sum(self.block_sizes.get(b[0], b[1])
                                for b in blocks[:-1])
                            + node["block_size"]) * repl
                    else:
                        removed_bytes += sum(
                            self.block_sizes.get(b[0], b[1])
                            for b in blocks) * repl
        with self._quota_mu:
            for k in children + [path]:
                self._quota_usage.pop(k, None)
        op = {"op": "delete", "path": path}
        self._log(op)
        self.apply_op(self.namespace, self.counters, op)
        self._charge(path, -(len(children) + 1), -removed_bytes)
        with self._blk:
            for bid in doomed:
                for addr in self.block_locations.pop(bid, set()):
                    self.commands.setdefault(addr, []).append(
                        {"type": "delete", "block_id": bid})
                self.block_sizes.pop(bid, None)
                self.block_to_path.pop(bid, None)
                self.hot_boost.pop(bid, None)
            self.total_known_blocks = max(
                0, self.total_known_blocks - counted_removed)
        return True

    def rename(self, src: str, dst: str) -> bool:
        # both subtrees' stripes, ascending (nslock sorts the union).
        # The dir-target rewrite below only APPENDS a component, which
        # never changes a >=depth path's stripe key, so locking the
        # caller's dst up front stays correct.
        with self._locked(src, dst, ensure=dst):
            self._check_safemode()
            if src not in self.namespace:
                return False
            user = self._caller()
            self._check_access(self._parent_of(src), 2, user)
            if dst in self.namespace and self.namespace[dst]["type"] == "dir":
                dst = dst.rstrip("/") + "/" + src.rsplit("/", 1)[-1]
            if dst in self.namespace:
                return False
            self._check_parent_write(dst, user)
            # the moved subtree charges dst-side quotas (FSDirectory.
            # verifyQuotaForRename); quota dirs already containing src
            # are net-zero and exempt
            sub_inodes, sub_bytes = self._subtree_usage(src)
            src_ino = self.namespace[src]
            if src_ino.get("type") == "file":
                sub_bytes += sum(self.block_sizes.get(b[0], b[1])
                                 for b in src_ino.get("blocks", [])) \
                    * src_ino.get("replication", 1)
            self._check_quota(
                dst,
                new_inodes=1 + sub_inodes + self._missing_ancestors(dst),
                new_bytes=sub_bytes, skip_ancestors_of=src)
            self._ensure_parents(dst, user)
            op = {"op": "rename", "path": src, "dst": dst}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            # blocks moved with their files: refresh the reverse index
            prefix = dst.rstrip("/") + "/"
            with self._blk:
                for k, v in self._ns_items():
                    if (k == dst or k.startswith(prefix)) \
                            and v.get("type") == "file":
                        for b in v.get("blocks", []):
                            self.block_to_path[b[0]] = k
            # quota counters: the subtree's usage leaves src's ancestors
            # and lands under dst's; cached entries for quota dirs INSIDE
            # the subtree move key
            src_prefix = src.rstrip("/") + "/"
            with self._quota_mu:
                moved_q = [(k, v) for k, v in self._quota_usage.items()
                           if k == src or k.startswith(src_prefix)]
                for k, v in moved_q:
                    del self._quota_usage[k]
                    self._quota_usage[dst + k[len(src):]] = v
            # open-file counted-block entries move with their paths, or
            # a later close would pop a stale/absent key and corrupt the
            # safemode denominator
            with self._blk:
                moved_uc = [k for k in self._uc_counted
                            if k == src or k.startswith(src_prefix)]
                for k in moved_uc:
                    self._uc_counted[dst + k[len(src):]] = \
                        self._uc_counted.pop(k)
            self._charge(src, -(1 + sub_inodes), -sub_bytes)
            self._charge(dst, 1 + sub_inodes, sub_bytes)
            self._audit("rename", src, dst=dst)
            return True

    def set_replication(self, path: str, replication: int) -> bool:
        with self._locked(path):
            self._check_safemode()
            inode = self._inode(path)
            if inode["type"] != "file":
                return False
            self._check_access(path, 2, self._caller())
            old = inode.get("replication", 1)
            size = sum(self.block_sizes.get(b[0], b[1])
                       for b in inode.get("blocks", []))
            if replication > old:
                self._check_quota(path, new_inodes=0,
                                  new_bytes=size * (replication - old))
            op = {"op": "set_repl", "path": path, "r": replication}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            self._charge(path, 0, size * (replication - old))
            self._audit("setReplication", path, perm=str(replication))
            return True

    def set_permission(self, path: str, mode: int) -> None:
        """chmod ≈ FSNamesystem.setPermission: owner or superuser only."""
        with self._locked(path):
            self._check_safemode()
            inode = self._inode(path)
            user = self._caller()
            if (self.permissions_enabled and user is not None
                    and user != self.superuser
                    and user != inode.get("owner", "")):
                raise PermissionError(
                    f"Permission denied: only the owner "
                    f"({inode.get('owner', '?')}) or the superuser may "
                    f"chmod {path}")
            op = {"op": "chmod", "path": path, "m": int(mode) & 0o7777}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            self._audit("setPermission", path,
                        perm=oct(int(mode) & 0o7777))

    def set_owner(self, path: str, owner: "str | None" = None,
                  group: "str | None" = None) -> None:
        """chown ≈ FSNamesystem.setOwner: owner changes need the superuser;
        the file owner may change its group to one of their own groups."""
        with self._locked(path):
            self._check_safemode()
            inode = self._inode(path)
            user = self._caller()
            if self.permissions_enabled and user is not None \
                    and user != self.superuser:
                if owner:
                    raise PermissionError(
                        "Permission denied: only the superuser may change "
                        f"the owner of {path}")
                if group and (user != inode.get("owner", "")
                              or group not in self._groups_of(user)):
                    raise PermissionError(
                        f"Permission denied: user={user} may not move "
                        f"{path} into group {group}")
            op = {"op": "chown", "path": path, "o": owner or "",
                  "g": group or ""}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            self._audit("setOwner", path,
                        perm=f"{owner or ''}:{group or ''}")

    def get_status(self, path: str) -> dict:
        with self._locked(path):
            inode = self._inode(path)
            perms = {"owner": inode.get("owner", ""),
                     "group": inode.get("group", ""),
                     "mode": inode.get("mode",
                                       0o755 if inode["type"] == "dir"
                                       else 0o644)}
            if inode["type"] == "dir":
                return {"path": path, "is_dir": True, "length": 0,
                        "mtime": inode.get("mtime", 0), **perms}
            length = sum(self.block_sizes.get(bid, size)
                         for bid, size in inode["blocks"])
            return {"path": path, "is_dir": False, "length": length,
                    "replication": inode["replication"],
                    "block_size": inode["block_size"],
                    "mtime": inode.get("mtime", 0),
                    "under_construction": bool(inode.get("uc")), **perms}

    def list_status(self, path: str) -> list[dict]:
        with self._locked(path):
            inode = self._inode(path)
            if inode["type"] != "dir":
                return [self.get_status(path)]
            prefix = path.rstrip("/") + "/"
            # snapshot scan: a shallow dir's listing spans stripes this
            # op does not hold — names from a GIL-atomic key snapshot,
            # statuses re-validated per child by get_status
            names = {k for k in list(self.namespace)
                     if k.startswith(prefix) and k != path
                     and "/" not in k[len(prefix):]}
            return [self.get_status(k) for k in sorted(names)]

    def exists(self, path: str) -> bool:
        # lock-free: a single dict membership test is GIL-atomic, and
        # any striped answer would be equally stale by return time
        return path in self.namespace

    # ------------------------------------------------------------ datanodes

    def register_datanode(self, addr: str, capacity: int) -> None:
        # rack resolution may exec the operator script — never under the
        # namesystem lock (a slow script would stall the control plane)
        rack = self.topology.add(addr)
        # admission check (may lazily read the hosts files) outside the
        # lock, like rack resolution above; the cached include/exclude
        # sets are replaced atomically by refresh_nodes
        admission = self._dn_admission(addr)
        with self._blk:
            if admission == "refuse":
                # ≈ DisallowedDatanodeException: host absent from a
                # configured dfs.hosts include list
                raise PermissionError(
                    f"datanode {addr} is not in the dfs.hosts include "
                    f"list; registration refused")
            self.datanodes[addr] = {"addr": addr, "capacity": capacity,
                                    "used": 0, "last_seen": _now(),
                                    # monotonic twin of last_seen: the
                                    # expiry deadline must survive NTP
                                    # steps (last_seen stays wall-clock
                                    # for the report/display surface)
                                    "seen_mono": time.monotonic(),
                                    "blocks": 0, "rack": rack}
            self.commands.setdefault(addr, [])
            if admission == "drain" and addr not in self.decommissioning:
                # excluded hosts register and immediately start draining
                # (verifyNodeRegistration's "registered but being
                # decommissioned" case)
                self._log_decommission(addr, "decommissioning")

    def dn_heartbeat(self, addr: str, used: int, capacity: int,
                     block_count: int,
                     hot_blocks: "dict | None" = None) -> list[dict]:
        with self._blk:
            info = self.datanodes.get(addr)
            if info is None:
                # unknown (expired / NN restarted): tell it to re-register
                # and send a fresh block report (≈ DNA_REGISTER)
                return [{"type": "register"}]
            info.update(used=used, capacity=capacity, last_seen=_now(),
                        seen_mono=time.monotonic(), blocks=block_count)
            cmds = self.commands.get(addr, [])
            self.commands[addr] = []
        # fold the piggybacked read-frequency slice OUTSIDE the
        # namespace lock (the hot-block table has its own leaf mutex);
        # a replace-fold means a re-delivered heartbeat is idempotent
        self.hot_blocks.fold(addr, hot_blocks)
        return cmds

    def block_report(self, addr: str, blocks: list[list[int]]) -> list[int]:
        """Full report: rebuild this node's locations; returns block ids the
        node should delete (orphans of deleted files)."""
        with self._blk:
            known = {bid for _, i in self._ns_items()
                     if i.get("type") == "file"
                     for bid, _ in i.get("blocks", [])}
            invalid: list[int] = []
            for locs in self.block_locations.values():
                locs.discard(addr)
            for bid, size in blocks:
                if bid in known:
                    self.block_locations.setdefault(bid, set()).add(addr)
                    self.block_sizes[bid] = size
                else:
                    invalid.append(bid)
            self._maybe_leave_safemode()
            return invalid

    def block_received(self, addr: str, block_id: int, size: int) -> None:
        with self._blk:
            self.block_locations.setdefault(block_id, set()).add(addr)
            self.block_sizes[block_id] = size
            self._maybe_leave_safemode()

    def _choose_targets(self, replication: int,
                        excluded: set[str]) -> list[str]:
        """Rack-aware placement ≈ ReplicationTargetChooser: the second
        replica goes to a DIFFERENT rack than the first (rack-failure
        tolerance), remaining replicas spread by load. On a flat topology
        (all /default-rack) this collapses to spread-by-load."""
        # decommissioning nodes take no NEW replicas (they are draining)
        live = [a for a, d in self.datanodes.items()
                if a not in excluded and a not in self.decommissioning]
        live.sort(key=lambda a: (self.datanodes[a]["used"], random.random()))
        if len(live) <= 1 or replication <= 1:
            return live[:replication]
        chosen = [live[0]]
        first_rack = self.topology.rack_of(live[0])
        rest = live[1:]
        off_rack = [a for a in rest
                    if self.topology.rack_of(a) != first_rack]
        if off_rack:
            chosen.append(off_rack[0])
            rest = [a for a in rest if a != off_rack[0]]
        for a in rest:
            if len(chosen) >= replication:
                break
            chosen.append(a)
        return chosen[:replication]

    # ------------------------------------------------------------ monitors

    def heartbeat_check(self, expiry_s: float) -> None:
        """Remove dead DataNodes; their replicas become under-replicated
        (≈ FSNamesystem.heartbeatCheck → removeDatanode)."""
        with self._blk:
            now = time.monotonic()
            dead = [a for a, d in self.datanodes.items()
                    if now - d.get("seen_mono", now) > expiry_s]
            for addr in dead:
                del self.datanodes[addr]
                self.commands.pop(addr, None)
                for locs in self.block_locations.values():
                    locs.discard(addr)
        for addr in dead:
            # a dead node's read counts leave the hot-block view with it
            self.hot_blocks.drop(addr)

    def replication_check(self) -> int:
        """One ReplicationMonitor sweep: schedule copies for
        under-replicated finalized blocks, deletes for over-replicated.
        Returns the number of commands scheduled. A hot-block boost
        (hotblock_check) raises a block's target above the file's
        replication; when the boost expires the same over-replication
        branch that trims manual set_replication drops trims it back."""
        with self._blk:
            if self.safemode or not self.datanodes:
                return 0
            healthy_nodes = [a for a in self.datanodes
                             if a not in self.decommissioning]
            scheduled = 0
            for path, inode in self._ns_items():
                if inode.get("type") != "file" or inode.get("uc"):
                    continue
                base_want = min(inode["replication"],
                                max(1, len(healthy_nodes)))
                for bid, _ in inode["blocks"]:
                    boost = self.hot_boost.get(bid, {}).get("boost", 0)
                    want = min(max(base_want, boost),
                               max(1, len(healthy_nodes)))
                    locs = {a for a in self.block_locations.get(bid, set())
                            if a in self.datanodes}
                    # replicas on draining nodes don't count toward the
                    # target (decommission = copy everything off first),
                    # but they remain valid COPY SOURCES
                    good = {a for a in locs
                            if a not in self.decommissioning}
                    if locs and len(good) < want:
                        targets = self._choose_targets(
                            want - len(good), excluded=locs)
                        if targets:
                            src = sorted(good or locs)[0]
                            self.commands.setdefault(src, []).append(
                                {"type": "replicate", "block_id": bid,
                                 "targets": targets})
                            scheduled += 1
                    elif len(good) > want:
                        for addr in sorted(good)[want:]:
                            self.commands.setdefault(addr, []).append(
                                {"type": "delete", "block_id": bid})
                            self.block_locations[bid].discard(addr)
                            scheduled += 1
            return scheduled

    def hotblock_check(self) -> int:
        """One hot-block policy sweep: close the loop from the cluster
        read-frequency view (datanode SpaceSaving sketches folded by
        dn_heartbeat) to replica placement. A block whose share of all
        tracked reads crosses ``tdfs.hotblocks.replicate.share`` (with a
        minimum absolute read count, so an idle cluster's 100%-share
        singleton block isn't "hot") gets a replication BOOST up to
        ``tdfs.hotblocks.replicate.cap``; the next replication_check
        sweep schedules the extra copies. A block that stops being hot
        for ``tdfs.hotblocks.cool.s`` loses the boost and the same sweep
        trims the extra replicas back. Returns boosted + expired count
        (a "changed" tally for the monitor log)."""
        rows = self.hot_blocks.top(32)
        total = self.hot_blocks.total_reads()
        now = time.monotonic()
        changed = 0
        with self._blk:
            if self.safemode:
                return 0
            cap = min(self.hot_cap, max(1, len(self.datanodes)))
            for r in rows:
                try:
                    bid = int(r["block"])
                except (TypeError, ValueError):
                    continue
                share = (r["reads"] / total) if total else 0.0
                if share >= self.hot_share and r["reads"] >= \
                        self.hot_min_reads:
                    if bid not in self.hot_boost:
                        changed += 1
                    self.hot_boost[bid] = {
                        "boost": cap, "share": share, "hot_mono": now}
            for bid in list(self.hot_boost):
                if now - self.hot_boost[bid]["hot_mono"] > self.hot_cool_s:
                    del self.hot_boost[bid]
                    changed += 1
        return changed

    def decommission_check(self) -> None:
        """Promote draining nodes to 'decommissioned' once every block
        they host has enough replicas elsewhere (≈ FSNamesystem.
        checkDecommissionStateInternal)."""
        with self._blk:
            for addr, state in list(self.decommissioning.items()):
                if state != "decommissioning":
                    continue
                if addr not in self.datanodes:
                    # died mid-drain: its blocks were NOT verified safe —
                    # stay 'decommissioning' so the operator sees the
                    # drain never completed (never report a dead node as
                    # safely decommissioned)
                    continue
                done = True
                for bid, locs in self.block_locations.items():
                    if addr not in locs:
                        continue
                    path = self.block_to_path.get(bid)
                    ino = self.namespace.get(path) if path else None
                    if ino is None:
                        continue
                    healthy = [a for a in self.datanodes
                               if a not in self.decommissioning]
                    want = min(ino.get("replication", 1),
                               max(1, len(healthy)))
                    good = {a for a in locs if a in self.datanodes
                            and a not in self.decommissioning}
                    if len(good) < want:
                        done = False
                        break
                if done:
                    self._log_decommission(addr, "decommissioned")

    def _log_decommission(self, addr: str, state: "str | None") -> None:
        op = {"op": "decommission", "addr": addr, "state": state}
        self._log(op)
        self.apply_op(self.namespace, self.counters, op)
        # counters may have been swapped by a checkpoint reload: re-bind
        self.decommissioning = self.counters.setdefault(
            "decommissioning", {})

    def refresh_nodes(self) -> dict:
        """≈ FSNamesystem.refreshNodes (dfsadmin -refreshNodes):
        re-read ``dfs.hosts`` / ``dfs.hosts.exclude`` and reconcile
        every known DataNode — removed-from-include ⇒ decommissioned
        outright; newly excluded ⇒ start draining; removed from exclude
        ⇒ stop draining. The stop case only applies when at least one
        hosts file is configured: an operator draining nodes via
        ``-decommission ADDR start`` (our addr-keyed alternative the
        reference lacks) must not have the drain silently canceled by a
        refresh against NO lists — a deliberate, documented divergence.
        Registration of disallowed hosts is refused
        (≈ verifyNodeRegistration / DisallowedDatanodeException)."""
        from tpumr.utils.hostsfile import read_hosts_lists
        # file I/O BEFORE the namesystem lock (same principle as rack
        # resolution in register_datanode: a slow NFS-mounted hosts
        # file must not stall every namespace RPC)
        include, exclude = read_hosts_lists(
            self.conf, "dfs.hosts", "dfs.hosts.exclude")
        with self._blk:
            self._check_superuser("refresh datanode admission lists")
            self._dn_include, self._dn_exclude = include, exclude
            # "configured" = the operator manages admission via FILES
            # (key set, even if currently empty — emptying the exclude
            # file is exactly how the reference un-drains everything);
            # only with NO keys do manual addr-keyed drains survive
            configured = bool(self.conf.get("dfs.hosts")) \
                or bool(self.conf.get("dfs.hosts.exclude"))
            changed: dict[str, str] = {}
            for addr in list(self.datanodes) + list(self.decommissioning):
                host = addr.split(":")[0]
                state = self.decommissioning.get(addr)
                if include is not None and host not in include:
                    # case 2 — but never flip a DEAD mid-drain node to
                    # "decommissioned": its blocks were not confirmed
                    # safe elsewhere (the decommission_check invariant)
                    if state != "decommissioned" \
                            and addr in self.datanodes:
                        self._log_decommission(addr, "decommissioned")
                        changed[addr] = "decommissioned"
                elif host in exclude:
                    if state is None:                    # case 3
                        self._log_decommission(addr, "decommissioning")
                        changed[addr] = "decommissioning"
                elif configured and state is not None:   # case 4
                    self._log_decommission(addr, None)
                    changed[addr] = "in-service"
            return {"included": (sorted(include) if include is not None
                                 else "*"),
                    "excluded": sorted(exclude),
                    "changed": changed}

    def _dn_admission(self, addr: str) -> str:
        """'refuse' (not in a configured include list), 'drain' (in the
        exclude list — registers, then decommissions, the reference's
        verifyNodeRegistration contract), or 'ok'."""
        if not hasattr(self, "_dn_include"):
            from tpumr.utils.hostsfile import read_hosts_lists
            self._dn_include, self._dn_exclude = read_hosts_lists(
                self.conf, "dfs.hosts", "dfs.hosts.exclude")
        host = addr.split(":")[0]
        if self._dn_include is not None and host not in self._dn_include:
            return "refuse"
        if host in self._dn_exclude:
            return "drain"
        return "ok"

    def set_decommission(self, addr: str, action: str = "start") -> str:
        """Admin: start/stop draining a DataNode (≈ dfsadmin exclude +
        refreshNodes). Journaled — the drain survives NN restarts.
        Returns the node's current state."""
        with self._blk:
            self._check_superuser("decommission datanodes")
            if action == "start" and addr not in self.decommissioning:
                self._log_decommission(addr, "decommissioning")
            elif action == "stop":
                self._log_decommission(addr, None)
            return self.decommissioning.get(addr, "in-service")

    def lease_check(self) -> None:
        """Expire hard-limit leases: finalize the file with whatever blocks
        were reported (lease recovery, simplified). Two-phase under
        striping: collect expired (client, paths) under the blocks lock,
        then recover each path under ITS stripe (journaling needs the
        stripe, and leases rank ABOVE stripes so the reverse nesting
        would violate the rank order). Each path re-validates — a writer
        renewing or completing between the phases wins."""
        # expiry runs on the monotonic twin (renewed_mono): a
        # wall-clock step must not mass-expire every writer's lease
        now = time.monotonic()
        with self._blk:
            expired = [(client, sorted(lease["paths"]))
                       for client, lease in self.leases.items()
                       if now - lease.get("renewed_mono", now)
                       > self.lease_hard_limit]
        for client, paths in expired:
            for path in paths:
                with self._locked(path):
                    with self._blk:
                        lease = self.leases.get(client)
                        if lease is None or now - lease.get(
                                "renewed_mono", now) <= \
                                self.lease_hard_limit:
                            break  # renewed since phase 1: nothing to do
                        inode = self.namespace.get(path)
                        if inode is None or not inode.get("uc") \
                                or inode.get("client") != client:
                            lease["paths"].discard(path)
                            continue
                        sizes = {str(bid): self.block_sizes.get(bid, size)
                                 for bid, size in inode["blocks"]}
                    op = {"op": "close", "path": path, "sizes": sizes}
                    self._log(op)
                    self.apply_op(self.namespace, self.counters, op)
                    with self._blk:
                        self.total_known_blocks += (
                            len(inode["blocks"])
                            - self._uc_counted.pop(path, 0))
                        lease = self.leases.get(client)
                        if lease is not None:
                            lease["paths"].discard(path)
            with self._blk:
                lease = self.leases.get(client)
                if lease is not None and not lease["paths"] \
                        and now - lease.get("renewed_mono", now) \
                        > self.lease_hard_limit:
                    del self.leases[client]

    # ------------------------------------------------------------ fsck

    def report_bad_block(self, block_id: int, addr: str) -> None:
        """Client found a checksum-corrupt replica (≈ ClientProtocol.
        reportBadBlocks): forget the location, tell the node to delete its
        copy, and let replication_check re-replicate from a good one.
        Safety rails: the caller must be able to READ the owning file
        (a report is as destructive as a delete), unknown blocks/locations
        are ignored, and the LAST live replica is never invalidated — a
        spurious report (or a transport error mistaken for corruption)
        must not be able to destroy the only copy (the HDFS rule)."""
        with self._blk:
            locs = self.block_locations.get(block_id)
            if not locs or addr not in locs:
                return
            path = self.block_to_path.get(block_id)
            if path is not None:
                self._check_access(path, 4, self._caller())
            self.corrupt_replicas.setdefault(block_id, set()).add(addr)
            if len(locs) <= 1:
                return  # recorded as corrupt, but keep the last copy
            locs.discard(addr)
            self.commands.setdefault(addr, []).append(
                {"type": "delete", "block_id": block_id})

    def fsck(self, path: str = "/") -> dict:
        """Namespace health walk ≈ NamenodeFsck.check: per-file block
        accounting against live replica locations. Needs a CONSISTENT
        namespace × block-map view, so it takes the structural lock
        (all stripes) plus the blocks lock — the one reader that still
        pays the full stop-the-world price, by design."""
        with self.locks.structural(), self._blk:
            report: dict = {"path": path, "files": 0, "dirs": 0,
                            "blocks": 0, "size": 0,
                            "under_replicated": [], "missing": [],
                            "corrupt": [], "over_replicated": [],
                            "open_files": []}
            prefix = "/" if path == "/" else path.rstrip("/") + "/"
            for p in sorted(self.namespace):
                if not (p == path or p.startswith(prefix)):
                    continue
                inode = self.namespace[p]
                if inode["type"] == "dir":
                    report["dirs"] += 1
                    continue
                if inode.get("uc"):
                    report["open_files"].append(p)
                    continue
                report["files"] += 1
                want = inode.get("replication", 1)
                for bid, size in inode.get("blocks", []):
                    report["blocks"] += 1
                    report["size"] += self.block_sizes.get(bid, size)
                    live = len(self.block_locations.get(bid, ()))
                    if bid in self.corrupt_replicas and live == 0:
                        report["corrupt"].append(
                            {"path": p, "block_id": bid,
                             "bad_replicas":
                                 sorted(self.corrupt_replicas[bid])})
                    elif live == 0:
                        report["missing"].append(
                            {"path": p, "block_id": bid})
                    elif live < want:
                        report["under_replicated"].append(
                            {"path": p, "block_id": bid,
                             "live": live, "want": want})
                    elif live > want:
                        report["over_replicated"].append(
                            {"path": p, "block_id": bid,
                             "live": live, "want": want})
            report["healthy"] = not (report["missing"] or report["corrupt"])
            return report

    def trash_emptier_check(self) -> int:
        """One Emptier pass over EVERY user's trash (≈ Trash.Emptier,
        which runs on the NameNode): seal each /user/<u>/.Trash/Current
        into a timestamp checkpoint and delete checkpoints older than
        fs.trash.interval. In-process calls bypass permissions — the
        emptier acts as the namesystem. Returns checkpoints expunged."""
        import re as _re
        interval_s = float(self.conf.get("fs.trash.interval", 0)) * 60
        if interval_s <= 0:
            return 0
        # key-snapshot scans (GIL-atomic): the emptier only needs a
        # candidate list — rename/delete below take their own stripes
        # and re-validate, so a racing writer is handled there
        roots = [p for p in list(self.namespace)
                 if _re.match(r"^/user/[^/]+/\.Trash$", p)]
        expunged = 0
        now = _now()
        for root in roots:
            current = root + "/Current"
            if current in self.namespace:
                ts = int(now)
                while f"{root}/{ts}" in self.namespace:
                    ts += 1
                self.rename(current, f"{root}/{ts}")
            stamps = [p for p in list(self.namespace)
                      if p.startswith(root + "/")
                      and p[len(root) + 1:].isdigit()
                      and "/" not in p[len(root) + 1:]]
            for stamp in stamps:
                if now - int(stamp.rsplit("/", 1)[1]) >= interval_s:
                    self.delete(stamp, recursive=True)
                    expunged += 1
        return expunged

    # ------------------------------------------------------------ admin

    def save_namespace(self) -> None:
        """Checkpoint in place (image ∪ edits → image; purge merged
        segments). Only the roll and the quota rebuild run under the
        namespace lock — the merge itself reads SEALED segments and the
        image, both owned by ``_ckpt_mu``, so a multi-second replay no
        longer stalls every client RPC (it used to run entirely under
        the lock)."""
        with self._ckpt_mu:
            with self.locks.structural():
                sealed = self.edits.roll()
                self._ckpt_token += 1  # invalidate any in-flight 2NN cycle
                self._checkpoint_segments = []
            namespace, counters = FSImage.load(self.name_dir)
            for op in FSEditLog.replay(self.name_dir, sealed):
                self.apply_op(namespace, counters, op)
            FSImage.save(self.name_dir, namespace, counters)
            FSEditLog.purge(sealed)
            with self.locks.structural():
                self._rebuild_quota_usage()  # self-heal conservative drift

    def edits_bytes(self) -> int:
        """On-disk journal size (auto-checkpoint trigger input)."""
        return self.edits.total_bytes()

    def get_name_state(self) -> dict:
        """Secondary checkpoint fetch (≈ GetImageServlet): ship the image
        plus every SEALED edit segment — as a LIST, preserving segment
        boundaries so the secondary's replay keeps per-segment torn-tail
        recovery (a concatenated blob would let one torn segment swallow
        the ops of every later one). The journal is rolled first; sealed
        segments are purged only when the merged image comes back with
        this fetch's token (put_image)."""
        import os
        from tpumr.dfs.editlog import IMAGE_NAME
        with self._ckpt_mu:
            with self.locks.structural():
                sealed = self.edits.roll()
                self._checkpoint_segments = sealed
                self._ckpt_token += 1  # fetch supersedes any earlier one
                token = self._ckpt_token
            # shipping the image + sealed segments is pure file I/O on
            # state frozen by _ckpt_mu — reading it under the namespace
            # lock would stall every client RPC for the transfer
            image = b"{}"
            img_path = os.path.join(self.name_dir, IMAGE_NAME)
            if os.path.exists(img_path):
                with open(img_path, "rb") as f:
                    image = f.read()
            segments = []
            for seg in sealed:
                try:
                    with open(seg, "rb") as f:
                        segments.append(f.read())
                except FileNotFoundError:
                    pass
            return {"image": image, "segments": segments,
                    "token": token}

    def put_image(self, image: bytes, token: int = -1) -> None:
        """Secondary checkpoint upload (≈ putFSImage + rollFSImage): make
        the merged image durable, THEN purge the segments it covers. The
        token must be the one handed out by the LATEST get_name_state —
        an upload from a superseded fetch (another secondary rolled the
        journal since, or an in-process checkpoint ran) is refused, since
        purging would delete edits its image does not contain."""
        import os
        from tpumr.dfs.editlog import IMAGE_NAME
        with self._ckpt_mu:
            with self.lock:
                # the token can't move while we hold _ckpt_mu (every
                # bump happens under it), so checking here then writing
                # outside the namespace lock is race-free in-process
                if token != self._ckpt_token:
                    raise RuntimeError(
                        "checkpoint signature mismatch: this merge is "
                        "from a superseded get_name_state fetch — "
                        "discarding it")
                segs = list(self._checkpoint_segments)
            tmp = os.path.join(self.name_dir, IMAGE_NAME + ".ckpt")
            with open(tmp, "wb") as f:
                f.write(image)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.name_dir, IMAGE_NAME))
            FSEditLog.purge(segs)
            with self.lock:
                self._checkpoint_segments = []

    def get_blocks(self, addr: str, max_blocks: int = 16) -> list[dict]:
        """Blocks hosted on one DataNode (≈ NamenodeProtocol.getBlocks —
        the balancer's feed)."""
        with self._blk:
            out = []
            for bid, locs in self.block_locations.items():
                if addr in locs:
                    out.append({"block_id": bid,
                                "size": self.block_sizes.get(bid, 0),
                                "locations": sorted(locs)})
                    if len(out) >= max_blocks:
                        break
            return out

    def remove_replica(self, addr: str, block_id: int) -> None:
        """Drop one replica (balancer move completion): forget the location
        and tell the node to delete its copy."""
        with self._blk:
            self.block_locations.get(block_id, set()).discard(addr)
            self.commands.setdefault(addr, []).append(
                {"type": "delete", "block_id": block_id})

    def datanode_report(self) -> list[dict]:
        with self._blk:
            out = []
            for addr, d in self.datanodes.items():
                row = dict(d)
                row["state"] = self.decommissioning.get(addr, "in-service")
                out.append(row)
            # decommissioned nodes that already left the cluster
            for addr, state in self.decommissioning.items():
                if addr not in self.datanodes:
                    out.append({"addr": addr, "state": state})
            return out

    def get_hot_blocks(self, n: int = 16) -> list[dict]:
        """Cluster-wide hottest blocks (merged datanode sketches),
        annotated with the owning path — the feed a future
        replicate/devcache-pin policy consumes (ROADMAP "DFS at
        production scale")."""
        rows = self.hot_blocks.top(int(n))
        with self._blk:
            for r in rows:
                try:
                    bid = int(r["block"])
                except (TypeError, ValueError):
                    r["path"] = ""
                    continue
                r["path"] = self.block_to_path.get(bid, "")
                r["replicas"] = len(self.block_locations.get(bid, ()))
                r["boost"] = self.hot_boost.get(bid, {}).get("boost", 0)
        return rows


#: method → service keys ≈ HDFSPolicyProvider: client ops (incl. the
#: dfsadmin surface, which rides ClientProtocol in the reference and is
#: additionally superuser-gated inside the namesystem), DataNode
#: reporting, and the 2NN/balancer NamenodeProtocol tier
NAMENODE_POLICY = {
    m: ["security.datanode.protocol.acl"]
    for m in ("register_datanode", "dn_heartbeat", "block_report",
              "block_received")
}
NAMENODE_POLICY.update({
    m: ["security.namenode.protocol.acl"]
    for m in ("get_name_state", "put_image", "get_blocks",
              "remove_replica")
})
NAMENODE_POLICY["report_bad_block"] = [
    "security.client.protocol.acl", "security.datanode.protocol.acl"]
NAMENODE_POLICY["refresh_service_acl"] = [
    "security.refresh.policy.protocol.acl"]
NAMENODE_POLICY["get_protocol_version"] = [
    "security.client.protocol.acl", "security.datanode.protocol.acl",
    "security.namenode.protocol.acl"]


class NameNode:
    """RPC daemon front (≈ NameNode.java): hosts the namesystem plus the
    monitor threads (heartbeat expiry, replication, lease recovery)."""

    def __init__(self, name_dir: str, conf: Any, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.conf = conf
        self.ns = FSNamesystem(name_dir, conf)
        self.dn_expiry_s = float(conf.get("tdfs.datanode.expiry.s", 10))
        # metrics live on the daemon whether or not HTTP is enabled —
        # the lock/editlog/op histograms must exist for bench_dfs and
        # the flight recorder even on a headless NN
        from tpumr.metrics import MetricsSystem
        self.metrics = MetricsSystem("namenode")
        self._mreg = self.metrics.new_registry("namenode")
        self.ns.bind_metrics(self._mreg)
        #: lazily-created per-op latency hists (nn_op_seconds{op=}) —
        #: the flight recorder windows these
        self._op_hists: dict[str, Any] = {}
        from tpumr.security import rpc_secret
        self._rpc_secret = rpc_secret(conf)
        self._server = RpcServer(self, host=host, port=port,
                                 secret=self._rpc_secret)
        # per-method rpc_<method> latency/request-size hists + inflight
        # gauges, same auto-instrumentation as the master's server
        self._server.metrics = self.metrics.new_registry("rpc")
        # per-service delegation tokens (≈ ClientProtocol.
        # getDelegationToken / DelegationTokenSecretManager): the
        # NameNode issues + tracks liveness for ITS tokens; JobTracker
        # tokens are a different service's and don't verify here
        from tpumr.security.tokens import TokenStore
        self.token_store = TokenStore(conf)
        self._server.token_store = self.token_store
        # service-level authorization ≈ hadoop-policy.xml (off unless
        # tpumr.security.authorization=true)
        from tpumr.security.authorize import ServiceAuthorizationManager
        self._server.authz = ServiceAuthorizationManager(
            conf, NAMENODE_POLICY, "security.client.protocol.acl")
        # impersonation rules (hadoop.proxyuser.*) are consulted from
        # the daemon conf; without this, doas frames are rejected
        self._server.proxy_conf = conf
        self._stop = threading.Event()
        self.killed = False
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="nn-monitors", daemon=True)
        self._http: Any = None
        self._http_port = int(conf.get("tdfs.http.port", -1))
        self.sampler: Any = None  # set by _build_http when prof enabled
        self.flightrec: Any = None  # armed in start() when SLO set

    def start(self) -> "NameNode":
        self._server.start()
        self._monitor.start()
        if self._http_port >= 0:
            self._http = self._build_http(self._http_port).start()
        # armed AFTER http so breach bundles carry folded stacks when
        # the profiler is on; tpumr.nn.incident.slo.ms=0 keeps it off
        from tpumr.metrics.flightrec import NNFlightRecorder
        self.flightrec = NNFlightRecorder.from_conf(self.conf, self,
                                                    self.sampler)
        if self.flightrec is not None:
            self.flightrec.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.flightrec is not None:
            self.flightrec.stop()
        if self.sampler is not None:
            self.sampler.stop()
        if self._http is not None:
            self._http.stop()
        self._server.stop()
        self.ns.edits.close()

    def kill(self) -> None:
        """SIGKILL-equivalent (the ``nn.crash`` / ``nn_restart`` chaos
        model): stop serving WITHOUT the clean-shutdown editlog close —
        the journal fd is abandoned exactly as a dead process leaves
        it, so the next NameNode on this name_dir must come up through
        image load + editlog replay (with torn-tail sealing) and earn
        its way out of safemode from block reports. In-flight client
        RPCs fail on the wire and ride the client retry policy."""
        self.killed = True
        self._stop.set()
        if self.flightrec is not None:
            self.flightrec.stop()
        if self.sampler is not None:
            self.sampler.stop()
        if self._http is not None:
            self._http.stop()
        self._server.stop()

    @property
    def http_url(self) -> "str | None":
        return self._http.url if self._http is not None else None

    def _build_http(self, port: int):
        """Status endpoints ≈ webapps/hdfs dfshealth.jsp + NameNodeMXBean."""
        from tpumr.http import StatusHttpServer
        srv = StatusHttpServer("namenode", port=port)

        # uniform /metrics (same payload shape as the mapred daemons —
        # one scraper config covers the whole cluster); the system
        # itself lives on the daemon (__init__) so the lock/op/editlog
        # series exist even before/without HTTP
        ms = self.metrics
        reg = self._mreg

        def _ns_gauges() -> dict:
            # lock-free snapshot scan (see FSNamesystem._ns_items): a
            # scrape must never queue behind — or stall — client ops
            items = self.ns._ns_items()
            return {
                "datanodes": len(self.ns.datanodes),
                "safemode": int(self.ns.safemode),
                "files": sum(1 for _, i in items
                             if i.get("type") == "file"),
                "blocks": sum(len(i.get("blocks", []))
                              for _, i in items),
                "audit_emitted": self.ns.audit_emitted,
                "audit_suppressed": self.ns.audit_suppressed,
            }

        reg.set_gauge("namespace", _ns_gauges)
        srv.attach_metrics(ms)

        # continuous profiler: same knob as the mapred daemons, so
        # enabling tpumr.prof.enabled lights /stacks + /flame here too
        from tpumr.metrics.sampler import StackSampler
        self.sampler = StackSampler.from_conf(self.conf, ms)
        if self.sampler is not None:
            self.sampler.start()
            self.sampler.attach_http(srv)

        def summary(q: dict) -> dict:
            ns = self.ns
            items = ns._ns_items()  # lock-free snapshot, like _ns_gauges
            files = sum(1 for _, i in items
                        if i.get("type") == "file")
            dirs = sum(1 for _, i in items
                       if i.get("type") == "dir")
            blocks = sum(len(i.get("blocks", []))
                         for _, i in items)
            return {"files": files, "directories": dirs, "blocks": blocks,
                    "safemode": ns.safemode,
                    "datanodes": len(ns.datanodes)}

        srv.add_json("namenode", summary)
        srv.add_json("datanodes", lambda q: self.ns.datanode_report())
        srv.add_json("fsck", lambda q: self.ns.fsck(q.get("path", "/")))

        # cluster-wide hot-block ranking (merged datanode SpaceSaving
        # slices) — a TOP-LEVEL tool surface like /metrics: the future
        # replicate/devcache-pin policy and operators read the same rows
        def hotblocks(q: dict) -> dict:
            n = int(q.get("n", 16))
            return {"total_reads": self.ns.hot_blocks.total_reads(),
                    "top": self.ns.get_hot_blocks(n)}

        srv.add_raw("hotblocks", hotblocks)
        srv.add_json("hotblocks", hotblocks)

        # incident bundles, same endpoints as the master so one
        # operator workflow covers both roles
        def incidents_json(q: dict) -> list:
            return (self.flightrec.list_incidents()
                    if self.flightrec is not None else [])

        def incident_raw(q: dict) -> dict:
            if self.flightrec is None:
                raise ValueError(
                    "NN flight recorder disabled "
                    "(tpumr.nn.incident.slo.ms is 0)")
            return self.flightrec.read_incident(q["name"])

        srv.add_json("incidents", incidents_json)
        srv.add_raw("incident", incident_raw)

        # HTML view ≈ webapps/hdfs/dfshealth.jsp
        from tpumr.http import html_escape, html_table

        fsck_cache: dict = {"ts": 0.0, "report": None}

        def cached_fsck() -> dict:
            """The full fsck walk holds the namesystem lock — cache it so
            dashboard refreshes/scrapers can't stall client RPCs by
            hammering '/' (≈ dfshealth.jsp reads cached FSNamesystem
            counters, it does not run fsck per request)."""
            import time as _time
            now = _time.monotonic()
            if fsck_cache["report"] is None or \
                    now - fsck_cache["ts"] > 10.0:
                fsck_cache["report"] = self.ns.fsck("/")
                fsck_cache["ts"] = now
            return fsck_cache["report"]

        def index_page(q: dict) -> str:
            s = summary(q)
            fsck = cached_fsck()
            rows = []
            for d in self.ns.datanode_report():
                cap = max(1, d.get("capacity", 1))
                used = d.get("used", 0)
                rows.append([
                    d.get("addr", "?"), d.get("rack", "?"),
                    f"{d.get('blocks', 0)}",
                    f"{used / 1e6:.1f} MB",
                    f"{100 * used / cap:.1f}%",
                ])
            health = ("<span class='ok'>HEALTHY</span>"
                      if fsck["healthy"]
                      else "<span class='bad'>CORRUPT</span>")
            return (
                f"<h1>NameNode — {html_escape(self.ns.name_dir)}</h1>"
                f"<p>{s['files']} files · {s['directories']} dirs · "
                f"{s['blocks']} blocks · "
                f"{'SAFEMODE · ' if s['safemode'] else ''}"
                f"{s['datanodes']} datanodes · filesystem {health}</p>"
                f"<p>under-replicated {len(fsck['under_replicated'])} · "
                f"missing {len(fsck['missing'])} · corrupt "
                f"{len(fsck['corrupt'])}</p><h2>DataNodes</h2>"
                + html_table(["address", "rack", "blocks", "used",
                              "used %"], rows))

        srv.add_page("index", index_page)
        return srv

    @property
    def address(self) -> tuple[str, int]:
        return self._server.address

    def _monitor_loop(self) -> None:
        interval = float(self.conf.get("tdfs.replication.interval.s", 1.0))
        # journal growth bound: checkpoint in-process once edits pass this
        # size, so the journal stays bounded even with no secondary
        # (≈ dfs.namenode.checkpoint.txns-style trigger); 0 disables
        auto_ckpt = int(float(self.conf.get(
            "tdfs.edits.auto.checkpoint.mb", 256)) * 1024 * 1024)
        # trash emptier cadence ≈ fs.trash.checkpoint.interval: default
        # one pass per trash interval, never more often than the monitor
        trash_every = float(self.conf.get(
            "fs.trash.checkpoint.interval.s",
            max(60.0, float(self.conf.get("fs.trash.interval", 0)) * 60)))
        from tpumr.utils.fi import fires
        last_trash = time.monotonic()
        while not self._stop.wait(interval):
            if fires("nn.crash", self.conf):
                # SIGKILL-equivalent chaos seam: the whole daemon dies
                # between monitor sweeps — restart/replay/safemode (and
                # clients riding RPC retries) are the quarry's predator
                self.kill()
                return
            try:
                self.ns.heartbeat_check(self.dn_expiry_s)
                # boosts must be set before the sweep that acts on them
                self.ns.hotblock_check()
                self.ns.replication_check()
                self.ns.lease_check()
                self.ns.decommission_check()
                self.token_store.purge_expired()
                if auto_ckpt and self.ns.edits_bytes() > auto_ckpt:
                    self.ns.save_namespace()
                if time.monotonic() - last_trash >= trash_every:
                    last_trash = time.monotonic()
                    self.ns.trash_emptier_check()
            except Exception:  # noqa: BLE001 — monitors must survive
                pass

    # ------------------------------------------------------------ RPC surface
    # thin delegation so the RPC registry exposes exactly the protocol

    def _op(self, name: str):
        """Per-op latency timer (``nn_op_seconds{op=}``, the labeled-
        family convention) wrapping each namespace RPC, plus the
        ``nn.op.slow`` fault seam — the stall lands inside the timed
        window but BEFORE the namespace lock, modelling a slow disk /
        GC pause on the op path; because the histogram sees it, it
        drives the NN incident e2e the way jt.heartbeat.slow drives
        the master's."""
        from tpumr.utils.fi import fires
        delay_s = 0.0
        if fires("nn.op.slow", self.conf):
            from tpumr.core import confkeys
            delay_s = confkeys.get_int(
                self.conf, "tpumr.fi.nn.op.slow.ms") / 1000.0
        h = self._op_hists.get(name)
        if h is None:
            h = self._mreg.histogram(f"nn_op_seconds|op={name}")
            self._op_hists[name] = h
        if not delay_s:
            return h.time()
        return self._op_stalled(h, delay_s)

    @staticmethod
    @contextlib.contextmanager
    def _op_stalled(h, delay_s: float):
        with h.time():
            time.sleep(delay_s)
            yield

    def get_protocol_version(self) -> int:
        return PROTOCOL_VERSION

    def create(self, path, client, replication=None, block_size=None,
               overwrite=True):
        with self._op("create"):
            return self.ns.create(path, client, replication, block_size,
                                  overwrite)

    def append(self, path, client):
        with self._op("append"):
            return self.ns.append(path, client)

    def fsync(self, path, client, last_block_size):
        with self._op("fsync"):
            return self.ns.fsync(path, client, last_block_size)

    def _mint_access(self, block_id, mode):
        """Short-lived per-block DataNode access stamp for the calling
        user (≈ BlockTokenSecretManager.generateToken, attached to
        located blocks). Only block-id-granting RPCs mint, so a
        canceled/expired delegation token stops yielding fresh stamps —
        DN access dies within the stamp lifetime."""
        if self._rpc_secret is None:
            return None
        from tpumr.ipc.rpc import current_rpc_user
        from tpumr.security.tokens import mint_block_access
        lifetime = float(self.conf.get("tpumr.block.access.lifetime.s",
                                       3600.0))
        return mint_block_access(self._rpc_secret,
                                 str(current_rpc_user() or ""),
                                 block_id, mode, lifetime)

    def add_block(self, path, client, prev_block_size=-1, excluded=None):
        with self._op("add_block"):
            out = self.ns.add_block(path, client, prev_block_size,
                                    excluded)
            access = self._mint_access(out["block_id"], "rw")
            if access is not None:
                out["access"] = access
            return out

    def abandon_block(self, path, client, block_id):
        with self._op("abandon_block"):
            return self.ns.abandon_block(path, client, block_id)

    def complete(self, path, client, last_block_size):
        with self._op("complete"):
            return self.ns.complete(path, client, last_block_size)

    def renew_lease(self, client):
        with self._op("renew_lease"):
            return self.ns.renew_lease(client)

    def get_block_locations(self, path):
        with self._op("get_block_locations"):
            out = self.ns.get_block_locations(path)
            if self._rpc_secret is not None:
                for b in out:
                    access = self._mint_access(b["block_id"], "r")
                    if access is not None:
                        b["access"] = access
            return out

    def mkdirs(self, path):
        with self._op("mkdirs"):
            return self.ns.mkdirs(path)

    # per-service delegation tokens ≈ ClientProtocol.getDelegationToken/
    # renewDelegationToken/cancelDelegationToken (DFSClient token path)

    def get_delegation_token(self, renewer=""):
        from tpumr.security.tokens import issue_for_caller
        return issue_for_caller(self.token_store, self._rpc_secret,
                                renewer)

    def renew_delegation_token(self, wire):
        from tpumr.ipc.rpc import current_rpc_user
        from tpumr.security.tokens import verify_wire
        tok = verify_wire(self._rpc_secret, wire)
        return self.token_store.renew(tok, str(current_rpc_user() or ""))

    def cancel_delegation_token(self, wire):
        from tpumr.ipc.rpc import current_rpc_user
        from tpumr.security.tokens import verify_wire
        tok = verify_wire(self._rpc_secret, wire)
        self.token_store.cancel(tok, str(current_rpc_user() or ""))
        return True

    def delete(self, path, recursive=True):
        with self._op("delete"):
            return self.ns.delete(path, recursive)

    def rename(self, src, dst):
        with self._op("rename"):
            return self.ns.rename(src, dst)

    def set_replication(self, path, replication):
        with self._op("set_replication"):
            return self.ns.set_replication(path, replication)

    def set_permission(self, path, mode):
        with self._op("set_permission"):
            return self.ns.set_permission(path, mode)

    def set_owner(self, path, owner=None, group=None):
        with self._op("set_owner"):
            return self.ns.set_owner(path, owner, group)

    def fsck(self, path="/"):
        with self._op("fsck"):
            return self.ns.fsck(path)

    def report_bad_block(self, block_id, addr):
        with self._op("report_bad_block"):
            return self.ns.report_bad_block(block_id, addr)

    def set_quota(self, path, ns_quota=None, sp_quota=None):
        with self._op("set_quota"):
            return self.ns.set_quota(path, ns_quota, sp_quota)

    def set_decommission(self, addr, action="start"):
        with self._op("set_decommission"):
            return self.ns.set_decommission(addr, action)

    def get_status(self, path):
        with self._op("get_status"):
            return self.ns.get_status(path)

    def list_status(self, path):
        with self._op("list_status"):
            return self.ns.list_status(path)

    def exists(self, path):
        with self._op("exists"):
            return self.ns.exists(path)

    def register_datanode(self, addr, capacity):
        with self._op("register_datanode"):
            return self.ns.register_datanode(addr, capacity)

    def dn_heartbeat(self, addr, used, capacity, block_count,
                     hot_blocks=None):
        with self._op("dn_heartbeat"):
            return self.ns.dn_heartbeat(addr, used, capacity,
                                        block_count, hot_blocks)

    def block_report(self, addr, blocks):
        with self._op("block_report"):
            return self.ns.block_report(addr, blocks)

    def block_received(self, addr, block_id, size):
        with self._op("block_received"):
            return self.ns.block_received(addr, block_id, size)

    def get_hot_blocks(self, n=16):
        with self._op("get_hot_blocks"):
            return self.ns.get_hot_blocks(n)

    def refresh_nodes(self):
        with self._op("refresh_nodes"):
            return self.ns.refresh_nodes()

    def refresh_service_acl(self) -> dict:
        """≈ RefreshAuthorizationPolicyProtocol.refreshServiceAcl
        (dfsadmin -refreshServiceAcl): re-read the policy (incl.
        tpumr.policy.file) live. The call itself is authorized by
        security.refresh.policy.protocol.acl; like the reference it
        refuses when authorization is off (a refresh that silently
        guards nothing misleads the operator)."""
        from tpumr.security.authorize import ServiceAuthorizationManager
        if self._server.authz is None or not self._server.authz.enabled:
            raise PermissionError(
                "service authorization is disabled "
                "(tpumr.security.authorization)")
        fresh = ServiceAuthorizationManager(
            self.conf, NAMENODE_POLICY, "security.client.protocol.acl")
        self._server.authz = fresh
        return fresh.acl_specs()

    def safemode(self, action="get"):
        if action == "leave":
            self.ns.safemode = False
        elif action == "enter":
            self.ns.safemode = True
        return self.ns.safemode

    def save_namespace(self):
        with self._op("save_namespace"):
            return self.ns.save_namespace()

    def get_name_state(self):
        with self._op("get_name_state"):
            return self.ns.get_name_state()

    def put_image(self, image, token=-1):
        with self._op("put_image"):
            return self.ns.put_image(image, token)

    def get_blocks(self, addr, max_blocks=16):
        with self._op("get_blocks"):
            return self.ns.get_blocks(addr, max_blocks)

    def remove_replica(self, addr, block_id):
        with self._op("remove_replica"):
            return self.ns.remove_replica(addr, block_id)

    def datanode_report(self):
        with self._op("datanode_report"):
            return self.ns.datanode_report()
