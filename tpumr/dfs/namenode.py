"""NameNode — namespace + block management master.

≈ ``org.apache.hadoop.hdfs.server.namenode.{NameNode,FSNamesystem}``
(reference: FSNamesystem.java, 5907 LoC; NameNode.java RPC front). Contracts
reproduced:

- flat namespace of files/dirs; files are ordered block lists; every
  mutation journals to the edit log BEFORE applying (editlog.py);
- single-writer leases: create() grants the lease, concurrent creates fail
  (AlreadyBeingCreatedException semantics); expired leases are recovered by
  finalizing the file with its reported blocks (LeaseManager);
- block locations are NOT persisted — rebuilt from DataNode block reports
  (BlocksMap + processReport semantics);
- safemode on startup until a threshold fraction of known blocks have a
  reported replica (``dfs.safemode.threshold.pct``, FSNamesystem.SafeModeInfo);
- heartbeat-lease liveness for DataNodes; a dead DataNode's replicas go
  under-replicated and the replication monitor schedules re-replication on
  surviving nodes (heartbeatCheck + ReplicationMonitor → DNA_TRANSFER /
  DNA_INVALIDATE commands piggybacked on heartbeats);
- write-path placement excludes client-reported bad nodes (abandonBlock +
  excludedNodes on addBlock).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any

from tpumr.dfs.editlog import FSEditLog, FSImage, checkpoint
from tpumr.ipc.rpc import RpcServer

#: ≈ ClientProtocol.versionID (hdfs/protocol/ClientProtocol.java)
PROTOCOL_VERSION = 61


class SafeModeError(RuntimeError):
    pass


class LeaseError(RuntimeError):
    pass


def _now() -> float:
    return time.time()


class FSNamesystem:
    """Namespace + block map + leases. All public mutators journal first."""

    def __init__(self, name_dir: str, conf: Any) -> None:
        self.conf = conf
        self.name_dir = name_dir
        self.lock = threading.RLock()
        self.default_replication = int(conf.get("dfs.replication", 3))
        self.default_block_size = int(conf.get("dfs.block.size",
                                               8 * 1024 * 1024))
        self.safemode_threshold = float(conf.get("dfs.safemode.threshold.pct",
                                                 0.999))
        self.lease_hard_limit = float(conf.get("tdfs.lease.hard.limit.s", 60))

        # persisted state: namespace + counters (image ∪ edits)
        self.namespace, self.counters = FSImage.load(name_dir)
        for op in FSEditLog.replay(name_dir):
            self.apply_op(self.namespace, self.counters, op)
        self.counters.setdefault("next_block", 1)
        self.counters.setdefault("gen", 1)
        if "/" not in self.namespace:
            self.namespace["/"] = {"type": "dir", "mtime": _now()}
        self.edits = FSEditLog(name_dir)

        # volatile state, rebuilt at runtime
        self.block_locations: dict[int, set[str]] = {}   # bid -> {dn addr}
        self.block_sizes: dict[int, int] = {}            # reported sizes
        self.datanodes: dict[str, dict] = {}             # addr -> info
        self.commands: dict[str, list[dict]] = {}        # addr -> pending
        self.leases: dict[str, dict] = {}                # client -> lease

        self.total_known_blocks = sum(
            len(i.get("blocks", [])) for i in self.namespace.values()
            if i.get("type") == "file")
        self.safemode = self.total_known_blocks > 0

        # rack awareness ≈ FSNamesystem's clusterMap (NetworkTopology)
        from tpumr.net import NetworkTopology, resolver_from_conf
        self.topology = NetworkTopology(resolver_from_conf(conf))

    # ------------------------------------------------------------ journal

    @staticmethod
    def apply_op(namespace: dict, counters: dict, op: dict) -> None:
        """Replay one journaled op onto a bare namespace. Shared by startup
        replay and checkpoint merge (editlog.checkpoint)."""
        kind = op["op"]
        p = op.get("path")
        if kind == "mkdir":
            namespace[p] = {"type": "dir", "mtime": op["t"]}
        elif kind == "create":
            namespace[p] = {"type": "file", "blocks": [], "uc": True,
                            "replication": op["r"], "block_size": op["bs"],
                            "mtime": op["t"], "client": op.get("c", "")}
        elif kind == "add_block":
            namespace[p]["blocks"].append([op["bid"], 0])
        elif kind == "block_size":
            for b in namespace[p]["blocks"]:
                if b[0] == op["bid"]:
                    b[1] = op["size"]
        elif kind == "abandon":
            namespace[p]["blocks"] = [b for b in namespace[p]["blocks"]
                                      if b[0] != op["bid"]]
        elif kind == "close":
            inode = namespace[p]
            inode["uc"] = False
            inode.pop("client", None)
            if "sizes" in op:
                for b in inode["blocks"]:
                    b[1] = op["sizes"].get(str(b[0]), b[1])
        elif kind == "rename":
            dst = op["dst"]
            moved = [(k, v) for k, v in namespace.items()
                     if k == p or k.startswith(p.rstrip("/") + "/")]
            for k, v in moved:
                del namespace[k]
                namespace[dst + k[len(p):]] = v
        elif kind == "delete":
            for k in [k for k in namespace
                      if k == p or k.startswith(p.rstrip("/") + "/")]:
                del namespace[k]
        elif kind == "set_repl":
            namespace[p]["replication"] = op["r"]
        elif kind == "counters":
            counters.update(op["values"])

    def _log(self, op: dict) -> None:
        self.edits.log(op)

    # ------------------------------------------------------------ helpers

    def _check_safemode(self) -> None:
        if self.safemode:
            raise SafeModeError(
                "NameNode is in safe mode: "
                f"{self._reported_fraction():.3f} of "
                f"{self.total_known_blocks} blocks reported "
                f"(threshold {self.safemode_threshold})")

    def _reported_fraction(self) -> float:
        if self.total_known_blocks == 0:
            return 1.0
        reported = sum(1 for i in self.namespace.values()
                       if i.get("type") == "file"
                       for b in i.get("blocks", [])
                       if self.block_locations.get(b[0]))
        return reported / self.total_known_blocks

    def _maybe_leave_safemode(self) -> None:
        if self.safemode and \
                self._reported_fraction() >= self.safemode_threshold:
            self.safemode = False

    def _ensure_parents(self, path: str) -> None:
        parts = [p for p in path.split("/") if p]
        cur = ""
        for part in parts[:-1]:
            cur += "/" + part
            inode = self.namespace.get(cur)
            if inode is None:
                self._log({"op": "mkdir", "path": cur, "t": _now()})
                self.namespace[cur] = {"type": "dir", "mtime": _now()}
            elif inode["type"] != "dir":
                raise NotADirectoryError(cur)

    def _inode(self, path: str) -> dict:
        inode = self.namespace.get(path)
        if inode is None:
            raise FileNotFoundError(path)
        return inode

    # ------------------------------------------------------------ client ops

    def create(self, path: str, client: str, replication: int | None,
               block_size: int | None, overwrite: bool) -> dict:
        with self.lock:
            self._check_safemode()
            existing = self.namespace.get(path)
            if existing is not None:
                if existing["type"] == "dir":
                    raise IsADirectoryError(path)
                if existing.get("uc"):
                    raise LeaseError(
                        f"{path} already being created by "
                        f"{existing.get('client')}")
                if not overwrite:
                    raise FileExistsError(path)
                self.delete(path)
            self._ensure_parents(path)
            r = replication or self.default_replication
            bs = block_size or self.default_block_size
            op = {"op": "create", "path": path, "r": r, "bs": bs,
                  "t": _now(), "c": client}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            lease = self.leases.setdefault(
                client, {"paths": set(), "renewed": _now()})
            lease["paths"].add(path)
            lease["renewed"] = _now()
            return {"replication": r, "block_size": bs}

    def add_block(self, path: str, client: str,
                  prev_block_size: int = -1,
                  excluded: list[str] | None = None) -> dict:
        with self.lock:
            self._check_safemode()
            inode = self._inode(path)
            if not inode.get("uc") or inode.get("client") != client:
                raise LeaseError(f"{client} does not hold the lease on {path}")
            if inode["blocks"] and prev_block_size >= 0:
                bid = inode["blocks"][-1][0]
                op = {"op": "block_size", "path": path, "bid": bid,
                      "size": prev_block_size}
                self._log(op)
                self.apply_op(self.namespace, self.counters, op)
            bid = self.counters["next_block"]
            gen = self.counters["gen"]
            self.counters["next_block"] = bid + 1
            self._log({"op": "counters", "values":
                       {"next_block": bid + 1, "gen": gen}})
            targets = self._choose_targets(inode["replication"],
                                           set(excluded or []))
            if not targets:
                raise IOError("no DataNodes available for replication")
            op = {"op": "add_block", "path": path, "bid": bid}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            return {"block_id": bid, "gen": gen, "targets": targets}

    def abandon_block(self, path: str, client: str, block_id: int) -> None:
        """Client hit a pipeline failure: drop the block and let it retry
        (≈ ClientProtocol.abandonBlock)."""
        with self.lock:
            op = {"op": "abandon", "path": path, "bid": block_id}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)

    def complete(self, path: str, client: str, last_block_size: int) -> None:
        with self.lock:
            inode = self._inode(path)
            if not inode.get("uc") or inode.get("client") != client:
                raise LeaseError(f"{client} does not hold the lease on {path}")
            sizes = {}
            if inode["blocks"] and last_block_size >= 0:
                sizes[str(inode["blocks"][-1][0])] = last_block_size
            op = {"op": "close", "path": path, "sizes": sizes}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            self.total_known_blocks += len(inode["blocks"])
            lease = self.leases.get(client)
            if lease:
                lease["paths"].discard(path)

    def renew_lease(self, client: str) -> None:
        with self.lock:
            lease = self.leases.get(client)
            if lease:
                lease["renewed"] = _now()

    def get_block_locations(self, path: str) -> list[dict]:
        with self.lock:
            inode = self._inode(path)
            if inode["type"] != "file":
                raise IsADirectoryError(path)
            out = []
            for bid, size in inode["blocks"]:
                locs = sorted(self.block_locations.get(bid, ()))
                out.append({"block_id": bid,
                            "size": self.block_sizes.get(bid, size),
                            "locations": locs})
            return out

    # ------------------------------------------------------------ namespace

    def mkdirs(self, path: str) -> bool:
        with self.lock:
            self._check_safemode()
            if path in self.namespace:
                return self.namespace[path]["type"] == "dir"
            self._ensure_parents(path + "/x")
            op = {"op": "mkdir", "path": path, "t": _now()}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            return True

    def delete(self, path: str, recursive: bool = True) -> bool:
        with self.lock:
            self._check_safemode()
            inode = self.namespace.get(path)
            if inode is None:
                return False
            children = [k for k in self.namespace
                        if k.startswith(path.rstrip("/") + "/")]
            if inode["type"] == "dir" and children and not recursive:
                raise OSError(f"{path} is a non-empty directory")
            # schedule replica invalidation on the owning DataNodes
            doomed: list[int] = []
            for k in children + [path]:
                node = self.namespace.get(k, {})
                if node.get("type") == "file":
                    doomed.extend(b[0] for b in node.get("blocks", []))
            op = {"op": "delete", "path": path}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            for bid in doomed:
                for addr in self.block_locations.pop(bid, set()):
                    self.commands.setdefault(addr, []).append(
                        {"type": "delete", "block_id": bid})
                self.block_sizes.pop(bid, None)
                self.total_known_blocks = max(0, self.total_known_blocks - 1)
            return True

    def rename(self, src: str, dst: str) -> bool:
        with self.lock:
            self._check_safemode()
            if src not in self.namespace:
                return False
            if dst in self.namespace and self.namespace[dst]["type"] == "dir":
                dst = dst.rstrip("/") + "/" + src.rsplit("/", 1)[-1]
            if dst in self.namespace:
                return False
            self._ensure_parents(dst)
            op = {"op": "rename", "path": src, "dst": dst}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            return True

    def set_replication(self, path: str, replication: int) -> bool:
        with self.lock:
            self._check_safemode()
            inode = self._inode(path)
            if inode["type"] != "file":
                return False
            op = {"op": "set_repl", "path": path, "r": replication}
            self._log(op)
            self.apply_op(self.namespace, self.counters, op)
            return True

    def get_status(self, path: str) -> dict:
        with self.lock:
            inode = self._inode(path)
            if inode["type"] == "dir":
                return {"path": path, "is_dir": True, "length": 0,
                        "mtime": inode.get("mtime", 0)}
            length = sum(self.block_sizes.get(bid, size)
                         for bid, size in inode["blocks"])
            return {"path": path, "is_dir": False, "length": length,
                    "replication": inode["replication"],
                    "block_size": inode["block_size"],
                    "mtime": inode.get("mtime", 0),
                    "under_construction": bool(inode.get("uc"))}

    def list_status(self, path: str) -> list[dict]:
        with self.lock:
            inode = self._inode(path)
            if inode["type"] != "dir":
                return [self.get_status(path)]
            prefix = path.rstrip("/") + "/"
            names = {k for k in self.namespace
                     if k.startswith(prefix) and k != path
                     and "/" not in k[len(prefix):]}
            return [self.get_status(k) for k in sorted(names)]

    def exists(self, path: str) -> bool:
        with self.lock:
            return path in self.namespace

    # ------------------------------------------------------------ datanodes

    def register_datanode(self, addr: str, capacity: int) -> None:
        # rack resolution may exec the operator script — never under the
        # namesystem lock (a slow script would stall the control plane)
        rack = self.topology.add(addr)
        with self.lock:
            self.datanodes[addr] = {"addr": addr, "capacity": capacity,
                                    "used": 0, "last_seen": _now(),
                                    "blocks": 0, "rack": rack}
            self.commands.setdefault(addr, [])

    def dn_heartbeat(self, addr: str, used: int, capacity: int,
                     block_count: int) -> list[dict]:
        with self.lock:
            info = self.datanodes.get(addr)
            if info is None:
                # unknown (expired / NN restarted): tell it to re-register
                # and send a fresh block report (≈ DNA_REGISTER)
                return [{"type": "register"}]
            info.update(used=used, capacity=capacity, last_seen=_now(),
                        blocks=block_count)
            cmds = self.commands.get(addr, [])
            self.commands[addr] = []
            return cmds

    def block_report(self, addr: str, blocks: list[list[int]]) -> list[int]:
        """Full report: rebuild this node's locations; returns block ids the
        node should delete (orphans of deleted files)."""
        with self.lock:
            known = {bid for i in self.namespace.values()
                     if i.get("type") == "file"
                     for bid, _ in i.get("blocks", [])}
            invalid: list[int] = []
            for locs in self.block_locations.values():
                locs.discard(addr)
            for bid, size in blocks:
                if bid in known:
                    self.block_locations.setdefault(bid, set()).add(addr)
                    self.block_sizes[bid] = size
                else:
                    invalid.append(bid)
            self._maybe_leave_safemode()
            return invalid

    def block_received(self, addr: str, block_id: int, size: int) -> None:
        with self.lock:
            self.block_locations.setdefault(block_id, set()).add(addr)
            self.block_sizes[block_id] = size
            self._maybe_leave_safemode()

    def _choose_targets(self, replication: int,
                        excluded: set[str]) -> list[str]:
        """Rack-aware placement ≈ ReplicationTargetChooser: the second
        replica goes to a DIFFERENT rack than the first (rack-failure
        tolerance), remaining replicas spread by load. On a flat topology
        (all /default-rack) this collapses to spread-by-load."""
        live = [a for a, d in self.datanodes.items() if a not in excluded]
        live.sort(key=lambda a: (self.datanodes[a]["used"], random.random()))
        if len(live) <= 1 or replication <= 1:
            return live[:replication]
        chosen = [live[0]]
        first_rack = self.topology.rack_of(live[0])
        rest = live[1:]
        off_rack = [a for a in rest
                    if self.topology.rack_of(a) != first_rack]
        if off_rack:
            chosen.append(off_rack[0])
            rest = [a for a in rest if a != off_rack[0]]
        for a in rest:
            if len(chosen) >= replication:
                break
            chosen.append(a)
        return chosen[:replication]

    # ------------------------------------------------------------ monitors

    def heartbeat_check(self, expiry_s: float) -> None:
        """Remove dead DataNodes; their replicas become under-replicated
        (≈ FSNamesystem.heartbeatCheck → removeDatanode)."""
        with self.lock:
            now = _now()
            dead = [a for a, d in self.datanodes.items()
                    if now - d["last_seen"] > expiry_s]
            for addr in dead:
                del self.datanodes[addr]
                self.commands.pop(addr, None)
                for locs in self.block_locations.values():
                    locs.discard(addr)

    def replication_check(self) -> int:
        """One ReplicationMonitor sweep: schedule copies for
        under-replicated finalized blocks, deletes for over-replicated.
        Returns the number of commands scheduled."""
        with self.lock:
            if self.safemode or not self.datanodes:
                return 0
            scheduled = 0
            for path, inode in self.namespace.items():
                if inode.get("type") != "file" or inode.get("uc"):
                    continue
                want = min(inode["replication"], len(self.datanodes))
                for bid, _ in inode["blocks"]:
                    locs = {a for a in self.block_locations.get(bid, set())
                            if a in self.datanodes}
                    if 0 < len(locs) < want:
                        targets = self._choose_targets(
                            want - len(locs), excluded=locs)
                        if targets:
                            src = sorted(locs)[0]
                            self.commands.setdefault(src, []).append(
                                {"type": "replicate", "block_id": bid,
                                 "targets": targets})
                            scheduled += 1
                    elif len(locs) > want:
                        for addr in sorted(locs)[want:]:
                            self.commands.setdefault(addr, []).append(
                                {"type": "delete", "block_id": bid})
                            self.block_locations[bid].discard(addr)
                            scheduled += 1
            return scheduled

    def lease_check(self) -> None:
        """Expire hard-limit leases: finalize the file with whatever blocks
        were reported (lease recovery, simplified)."""
        with self.lock:
            now = _now()
            for client, lease in list(self.leases.items()):
                if now - lease["renewed"] <= self.lease_hard_limit:
                    continue
                for path in list(lease["paths"]):
                    inode = self.namespace.get(path)
                    if inode is None or not inode.get("uc"):
                        continue
                    op = {"op": "close", "path": path, "sizes": {
                        str(bid): self.block_sizes.get(bid, size)
                        for bid, size in inode["blocks"]}}
                    self._log(op)
                    self.apply_op(self.namespace, self.counters, op)
                    self.total_known_blocks += len(inode["blocks"])
                del self.leases[client]

    # ------------------------------------------------------------ admin

    def save_namespace(self) -> None:
        """Checkpoint in place (image ∪ edits → image; truncate edits)."""
        with self.lock:
            self.edits.close()
            checkpoint(self.name_dir, self.apply_op)
            self.edits = FSEditLog(self.name_dir)

    def get_name_state(self) -> dict:
        """Secondary checkpoint fetch (≈ GetImageServlet): returns the
        current image + edits and ROLLS the journal, so edits after this
        point replay cleanly on top of the merged image the secondary will
        upload."""
        import os
        from tpumr.dfs.editlog import EDITS_NAME, IMAGE_NAME
        with self.lock:
            image = b"{}"
            img_path = os.path.join(self.name_dir, IMAGE_NAME)
            if os.path.exists(img_path):
                with open(img_path, "rb") as f:
                    image = f.read()
            with open(os.path.join(self.name_dir, EDITS_NAME), "rb") as f:
                edits = f.read()
            self.edits.roll()
            return {"image": image, "edits": edits}

    def put_image(self, image: bytes) -> None:
        """Secondary checkpoint upload (≈ putFSImage + rollFSImage)."""
        import os
        from tpumr.dfs.editlog import IMAGE_NAME
        with self.lock:
            tmp = os.path.join(self.name_dir, IMAGE_NAME + ".ckpt")
            with open(tmp, "wb") as f:
                f.write(image)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.name_dir, IMAGE_NAME))

    def get_blocks(self, addr: str, max_blocks: int = 16) -> list[dict]:
        """Blocks hosted on one DataNode (≈ NamenodeProtocol.getBlocks —
        the balancer's feed)."""
        with self.lock:
            out = []
            for bid, locs in self.block_locations.items():
                if addr in locs:
                    out.append({"block_id": bid,
                                "size": self.block_sizes.get(bid, 0),
                                "locations": sorted(locs)})
                    if len(out) >= max_blocks:
                        break
            return out

    def remove_replica(self, addr: str, block_id: int) -> None:
        """Drop one replica (balancer move completion): forget the location
        and tell the node to delete its copy."""
        with self.lock:
            self.block_locations.get(block_id, set()).discard(addr)
            self.commands.setdefault(addr, []).append(
                {"type": "delete", "block_id": block_id})

    def datanode_report(self) -> list[dict]:
        with self.lock:
            return [dict(d) for d in self.datanodes.values()]


class NameNode:
    """RPC daemon front (≈ NameNode.java): hosts the namesystem plus the
    monitor threads (heartbeat expiry, replication, lease recovery)."""

    def __init__(self, name_dir: str, conf: Any, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.conf = conf
        self.ns = FSNamesystem(name_dir, conf)
        self.dn_expiry_s = float(conf.get("tdfs.datanode.expiry.s", 10))
        from tpumr.security import rpc_secret
        self._server = RpcServer(self, host=host, port=port,
                                 secret=rpc_secret(conf))
        self._stop = threading.Event()
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="nn-monitors", daemon=True)
        self._http: Any = None
        self._http_port = int(conf.get("tdfs.http.port", -1))

    def start(self) -> "NameNode":
        self._server.start()
        self._monitor.start()
        if self._http_port >= 0:
            self._http = self._build_http(self._http_port).start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._http is not None:
            self._http.stop()
        self._server.stop()
        self.ns.edits.close()

    @property
    def http_url(self) -> "str | None":
        return self._http.url if self._http is not None else None

    def _build_http(self, port: int):
        """Status endpoints ≈ webapps/hdfs dfshealth.jsp + NameNodeMXBean."""
        from tpumr.http import StatusHttpServer
        srv = StatusHttpServer("namenode", port=port)

        def summary(q: dict) -> dict:
            ns = self.ns
            with ns.lock:
                files = sum(1 for i in ns.namespace.values()
                            if i.get("type") == "file")
                dirs = sum(1 for i in ns.namespace.values()
                           if i.get("type") == "dir")
                blocks = sum(len(i.get("blocks", []))
                             for i in ns.namespace.values())
            return {"files": files, "directories": dirs, "blocks": blocks,
                    "safemode": ns.safemode,
                    "datanodes": len(ns.datanodes)}

        srv.add_json("namenode", summary)
        srv.add_json("datanodes", lambda q: self.ns.datanode_report())
        return srv

    @property
    def address(self) -> tuple[str, int]:
        return self._server.address

    def _monitor_loop(self) -> None:
        interval = float(self.conf.get("tdfs.replication.interval.s", 1.0))
        while not self._stop.wait(interval):
            try:
                self.ns.heartbeat_check(self.dn_expiry_s)
                self.ns.replication_check()
                self.ns.lease_check()
            except Exception:  # noqa: BLE001 — monitors must survive
                pass

    # ------------------------------------------------------------ RPC surface
    # thin delegation so the RPC registry exposes exactly the protocol

    def get_protocol_version(self) -> int:
        return PROTOCOL_VERSION

    def create(self, path, client, replication=None, block_size=None,
               overwrite=True):
        return self.ns.create(path, client, replication, block_size,
                              overwrite)

    def add_block(self, path, client, prev_block_size=-1, excluded=None):
        return self.ns.add_block(path, client, prev_block_size, excluded)

    def abandon_block(self, path, client, block_id):
        return self.ns.abandon_block(path, client, block_id)

    def complete(self, path, client, last_block_size):
        return self.ns.complete(path, client, last_block_size)

    def renew_lease(self, client):
        return self.ns.renew_lease(client)

    def get_block_locations(self, path):
        return self.ns.get_block_locations(path)

    def mkdirs(self, path):
        return self.ns.mkdirs(path)

    def delete(self, path, recursive=True):
        return self.ns.delete(path, recursive)

    def rename(self, src, dst):
        return self.ns.rename(src, dst)

    def set_replication(self, path, replication):
        return self.ns.set_replication(path, replication)

    def get_status(self, path):
        return self.ns.get_status(path)

    def list_status(self, path):
        return self.ns.list_status(path)

    def exists(self, path):
        return self.ns.exists(path)

    def register_datanode(self, addr, capacity):
        return self.ns.register_datanode(addr, capacity)

    def dn_heartbeat(self, addr, used, capacity, block_count):
        return self.ns.dn_heartbeat(addr, used, capacity, block_count)

    def block_report(self, addr, blocks):
        return self.ns.block_report(addr, blocks)

    def block_received(self, addr, block_id, size):
        return self.ns.block_received(addr, block_id, size)

    def safemode(self, action="get"):
        if action == "leave":
            self.ns.safemode = False
        elif action == "enter":
            self.ns.safemode = True
        return self.ns.safemode

    def save_namespace(self):
        return self.ns.save_namespace()

    def get_name_state(self):
        return self.ns.get_name_state()

    def put_image(self, image):
        return self.ns.put_image(image)

    def get_blocks(self, addr, max_blocks=16):
        return self.ns.get_blocks(addr, max_blocks)

    def remove_replica(self, addr, block_id):
        return self.ns.remove_replica(addr, block_id)

    def datanode_report(self):
        return self.ns.datanode_report()
