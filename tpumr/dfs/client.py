"""DFSClient — write pipeline + replica-failover reads + lease renewal.

≈ ``org.apache.hadoop.hdfs.DFSClient`` (reference: hdfs/DFSClient.java,
3958 LoC). Contracts reproduced:

- writes buffer client-side and ship full blocks down a DataNode pipeline;
  a failed pipeline abandons the block, re-requests targets excluding the
  bad node, and retries (DFSOutputStream.processDatanodeError);
- reads fetch the block map once, then fail over across replicas on
  IOError/checksum mismatch (DFSInputStream.chooseDataNode + seekToNewSource);
- a background thread renews the client lease while files are open for
  write (LeaseRenewer).

Transport: DataNode connections come from a shared ``RpcClientPool``
(the shuffle copier's engine) — at most ``tdfs.client.dn.conns`` warm
sockets per datanode, idle ones evicted after ``tdfs.client.dn.idle.s``
(the old per-addr client cache grew one socket per datanode ever
contacted and never closed any). A lease is exclusive, so the chunk
streams PIPELINE: ``tdfs.client.read.pipeline.depth`` read requests ride
the wire back-to-back and the datanode overlaps its pread+CRC work with
the client's drain, instead of one ping-pong RTT per chunk.
"""

from __future__ import annotations

import io
import threading
import time
import uuid
from typing import Any

from tpumr.core import tracing as _tracing
from tpumr.io import compress as _compress
from tpumr.ipc.rpc import RpcClient, RpcClientPool, RpcError


class DFSClient:
    def __init__(self, host: str, port: int, conf: Any = None) -> None:
        self.conf = conf
        from tpumr.security import client_credentials
        self._secret, self._scope = client_credentials(conf, "namenode")
        # NN transport retries: resends carry the same (cid, id), so
        # the server's replay cache makes them exact-once even for
        # mutations. With backoff these are what carry a client ACROSS
        # a NameNode restart (the nn_restart chaos contract) instead of
        # surfacing every outage as an immediate IOError.
        self.nn = RpcClient(
            host, int(port), secret=self._secret, scope=self._scope,
            retries=int(self._conf_get("tdfs.client.nn.retries", 1)),
            backoff_ms=float(self._conf_get(
                "tdfs.client.nn.backoff.ms", 200.0)))
        self.name = f"TDFSClient_{uuid.uuid4().hex[:12]}"
        self._dn_pool = RpcClientPool(
            self._dn_factory,
            conns_per_target=int(self._conf_get("tdfs.client.dn.conns",
                                                2)),
            idle_s=float(self._conf_get("tdfs.client.dn.idle.s", 60.0)))
        #: wire codec OFFERED on chunk reads — resolved once to a codec
        #: this process decodes at native speed, else "none"
        self._read_wire = _compress.wire_codec_or_none(
            str(self._conf_get("tdfs.read.wire.codec", "tlz")))
        #: block_id -> NameNode access stamp (≈ LocatedBlock.blockToken)
        self._block_access: dict[int, Any] = {}
        self._lock = threading.Lock()
        self._open_writes = 0
        self._renewer: threading.Thread | None = None
        self._stop_renew = threading.Event()

    def _conf_get(self, key: str, default: Any) -> Any:
        return default if self.conf is None else self.conf.get(key,
                                                               default)

    # ------------------------------------------------------------ dn plumbing

    def _dn_factory(self, host: str, port: int) -> RpcClient:
        cli = RpcClient(host, int(port), secret=self._secret,
                        scope=self._scope)
        cli.envelope_provider = self._dn_envelope
        return cli

    def _dn_call(self, addr: str, method: str, *params: Any) -> Any:
        """One plain call on a pooled lease (non-pipelined callers)."""
        cli = self._dn_pool.acquire(addr)
        try:
            out = cli.call(method, *params)
        except BaseException:
            self._dn_pool.release(addr, cli, dead=True)
            raise
        self._dn_pool.release(addr, cli)
        return out

    def close(self) -> None:
        """Release every pooled datanode socket and stop the renewer.
        The client stays usable for NameNode ops afterwards only by
        accident — treat it as closed."""
        self._stop_renew.set()
        self._dn_pool.close()
        self.nn.close()

    def _dn_envelope(self, method: str, params: tuple) -> "dict | None":
        """Attach the NameNode-minted block-access stamp to DataNode
        calls (personal-credential clients only — daemons don't need
        one). Stamps arrive on get_block_locations/add_block responses."""
        if self._scope is None or not params:
            return None
        try:
            stamp = self._block_access.get(int(params[0]))
        except (TypeError, ValueError):
            return None
        return {"access": stamp} if stamp is not None else None

    def _remember_access(self, block_id: Any, stamp: Any) -> None:
        if stamp is None:
            return
        if len(self._block_access) > 8192:   # bound a long-lived client
            self._block_access.clear()
        self._block_access[int(block_id)] = stamp

    # ------------------------------------------------------------ lease

    def _writer_opened(self) -> None:
        with self._lock:
            self._open_writes += 1
            if self._renewer is None:
                self._stop_renew.clear()
                self._renewer = threading.Thread(
                    target=self._renew_loop, name="lease-renewer",
                    daemon=True)
                self._renewer.start()

    def _writer_closed(self) -> None:
        with self._lock:
            self._open_writes = max(0, self._open_writes - 1)
            if self._open_writes == 0:
                self._stop_renew.set()
                self._renewer = None

    def _renew_loop(self) -> None:
        period = 5.0
        if self.conf is not None:
            period = float(self.conf.get("tdfs.lease.hard.limit.s", 60)) / 4
        while not self._stop_renew.wait(period):
            try:
                self.nn.call("renew_lease", self.name)
            except RpcError:
                pass

    # ------------------------------------------------------------ write

    def create(self, path: str, overwrite: bool = True,
               replication: int | None = None,
               block_size: int | None = None) -> "_DFSOutputStream":
        meta = self.nn.call("create", path, self.name, replication,
                            block_size, overwrite)
        self._writer_opened()
        return _DFSOutputStream(self, path, meta["block_size"])

    def append(self, path: str) -> "_DFSOutputStream":
        """Reopen a complete file for block-granular append (≈
        DFSClient.append, hdfs/DFSClient.java): appended data lands in
        new blocks; ``hflush()`` publishes it to readers mid-write."""
        meta = self.nn.call("append", path, self.name)
        self._writer_opened()
        return _DFSOutputStream(self, path, meta["block_size"])

    # ------------------------------------------------------------ read

    def open(self, path: str) -> io.BufferedReader:
        blocks = self.nn.call("get_block_locations", path)
        for b in blocks:
            self._remember_access(b["block_id"], b.get("access"))
        return io.BufferedReader(_DFSInputStream(self, blocks, path))

    # ------------------------------------------------------------ namespace

    def mkdirs(self, path: str) -> bool:
        return self.nn.call("mkdirs", path)

    def delete(self, path: str, recursive: bool = True) -> bool:
        return self.nn.call("delete", path, recursive)

    def rename(self, src: str, dst: str) -> bool:
        return self.nn.call("rename", src, dst)

    def exists(self, path: str) -> bool:
        return self.nn.call("exists", path)

    def get_status(self, path: str) -> dict:
        return self.nn.call("get_status", path)

    def list_status(self, path: str) -> list[dict]:
        return self.nn.call("list_status", path)

    def set_replication(self, path: str, replication: int) -> bool:
        return self.nn.call("set_replication", path, replication)

    def set_permission(self, path: str, mode: int) -> None:
        self.nn.call("set_permission", path, mode)

    def set_owner(self, path: str, owner: "str | None" = None,
                  group: "str | None" = None) -> None:
        self.nn.call("set_owner", path, owner, group)

    def fsck(self, path: str = "/") -> dict:
        return self.nn.call("fsck", path)

    def datanode_report(self) -> list[dict]:
        return self.nn.call("datanode_report")


class _DFSOutputStream(io.RawIOBase):
    """Buffer → block pipeline writer (≈ DFSOutputStream)."""

    MAX_BLOCK_RETRIES = 3

    def __init__(self, client: DFSClient, path: str, block_size: int) -> None:
        self.client = client
        self.path = path
        self.block_size = block_size
        self._buf = bytearray()
        self._prev_block_size = -1
        self._closed = False

    def writable(self) -> bool:
        return True

    def write(self, data: bytes) -> int:  # type: ignore[override]
        self._buf.extend(data)
        while len(self._buf) >= self.block_size:
            chunk = bytes(self._buf[: self.block_size])
            del self._buf[: self.block_size]
            self._flush_block(chunk)
        return len(data)

    def _flush_block(self, data: bytes) -> None:
        with _tracing.span("dfs.write", path=self.path,
                           bytes=len(data)):
            self._flush_block_traced(data)

    def _flush_block_traced(self, data: bytes) -> None:
        excluded: list[str] = []
        last_err: Exception | None = None
        chunk = int(self.client._conf_get("tdfs.client.write.chunk.bytes",
                                          1 << 20))
        depth = max(1, int(self.client._conf_get(
            "tdfs.client.write.pipeline.depth", 4)))
        for _ in range(self.MAX_BLOCK_RETRIES):
            alloc = self.client.nn.call("add_block", self.path,
                                        self.client.name,
                                        self._prev_block_size, excluded)
            bid, targets = alloc["block_id"], alloc["targets"]
            self.client._remember_access(bid, alloc.get("access"))
            # prev size is journaled now; next add_block must not re-log it
            self._prev_block_size = -1
            try:
                self._ship_block(bid, targets, data, chunk, depth)
                self._prev_block_size = len(data)
                return
            except Exception as e:  # noqa: BLE001 — pipeline failure
                last_err = e
                excluded.append(targets[0])
                self.client.nn.call("abandon_block", self.path,
                                    self.client.name, bid)
        raise IOError(f"write pipeline failed for {self.path} after "
                      f"{self.MAX_BLOCK_RETRIES} attempts: {last_err}")

    def _ship_block(self, bid: int, targets: "list[str]", data: bytes,
                    chunk: int, depth: int) -> None:
        """Ship one block to the pipeline head on a pooled lease. Small
        blocks ride one RPC; larger ones stream as bounded chunks with
        up to ``depth`` appends on the wire (each ack still means the
        whole DN chain appended — commit is the durability barrier, so
        overlapping the acks changes latency, not the contract)."""
        pool = self.client._dn_pool
        cli = pool.acquire(targets[0])
        try:
            if len(data) <= chunk:
                # small blocks: the single-shot path (one RPC)
                cli.call("write_block", bid, data, targets[1:])
            else:
                # streamed pipeline (≈ DataTransferProtocol
                # WRITE_BLOCK): bounded chunks relay DN→DN→DN; the
                # commit only returns once every replica installed
                cli.call("open_block_stream", bid, targets[1:])
                try:
                    spans = list(range(0, len(data), chunk))
                    sent = 0
                    for _done in range(len(spans)):
                        while sent < len(spans) and sent - _done < depth:
                            lo = spans[sent]
                            cli.call_begin("write_block_chunk", bid,
                                           data[lo:lo + chunk])
                            sent += 1
                        cli.call_finish()
                    cli.call("commit_block_stream", bid)
                except Exception:
                    # the lease is dead after a mid-window failure —
                    # abort on a FRESH lease so the datanode's temp
                    # state is cleaned even though this socket is gone
                    try:
                        self.client._dn_call(targets[0],
                                             "abort_block_stream", bid)
                    except Exception:  # noqa: BLE001 — best effort
                        pass
                    raise
        except BaseException:
            pool.release(targets[0], cli, dead=True)
            raise
        pool.release(targets[0], cli)

    def hflush(self) -> None:
        """Make everything written so far visible to readers (≈
        DFSOutputStream.sync/hflush): flush the buffer as a (possibly
        short) block, then have the NameNode journal its true size.
        Log-style writers call this at record boundaries; each hflush
        seals a block, so batch accordingly (block-granular append)."""
        if self._buf:
            data = bytes(self._buf)
            self._buf.clear()
            self._flush_block(data)
        if self._prev_block_size >= 0:
            self.client.nn.call("fsync", self.path, self.client.name,
                                self._prev_block_size)
            # size is journaled — add_block/close must not re-settle it
            self._prev_block_size = -1

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            last_size = -1
            if self._buf:
                data = bytes(self._buf)
                self._buf.clear()
                self._flush_block(data)
                last_size = len(data)
            elif self._prev_block_size >= 0:
                last_size = self._prev_block_size
            self.client.nn.call("complete", self.path, self.client.name,
                                last_size)
        finally:
            self.client._writer_closed()
            super().close()


class _DFSInputStream(io.RawIOBase):
    """Positioned reads over the block map with replica failover
    (≈ DFSInputStream)."""

    def __init__(self, client: DFSClient, blocks: list[dict],
                 path: "str | None" = None) -> None:
        self.client = client
        self.blocks = blocks
        self.path = path
        self.length = sum(b["size"] for b in blocks)
        self.pos = 0

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, offset: int, whence: int = io.SEEK_SET) -> int:
        if whence == io.SEEK_SET:
            self.pos = offset
        elif whence == io.SEEK_CUR:
            self.pos += offset
        else:
            self.pos = self.length + offset
        return self.pos

    def tell(self) -> int:
        return self.pos

    def readinto(self, b: bytearray) -> int:  # type: ignore[override]
        if self.pos >= self.length:
            return 0
        want = min(len(b), self.length - self.pos)
        out = self._pread(self.pos, want)
        b[: len(out)] = out
        self.pos += len(out)
        return len(out)

    def _pread(self, pos: int, length: int) -> bytes:
        chunks: list[bytes] = []
        offset = 0
        for blk in self.blocks:
            size = blk["size"]
            if pos >= offset + size:
                offset += size
                continue
            if pos + length <= offset:
                break
            lo = max(pos, offset) - offset
            hi = min(pos + length, offset + size) - offset
            chunks.append(self._read_replica(blk, lo, hi - lo))
            offset += size
        return b"".join(chunks)

    def _read_replica(self, blk: dict, offset: int, length: int) -> bytes:
        with _tracing.span("dfs.read", block_id=blk["block_id"],
                           bytes=length):
            retries = max(0, int(self.client._conf_get(
                "tdfs.client.read.acquire.retries", 3)))
            backoff = float(self.client._conf_get(
                "tdfs.client.read.acquire.backoff.ms", 300.0)) / 1000.0
            last: "Exception | None" = None
            for attempt in range(retries + 1):
                if attempt:
                    # cached locations are exhausted or EMPTY — a
                    # restarted/expiring NameNode window, not a dead
                    # block. Refetch from the NN and retry against the
                    # fresh replica set (≈ DFSInputStream's
                    # chooseDataNode refetch, bounded like
                    # dfs.client.max.block.acquire.failures). A
                    # safemode refusal propagates to the caller's own
                    # retry policy.
                    time.sleep(backoff)
                    self._refetch_locations(blk)
                try:
                    return self._read_replica_traced(blk, offset,
                                                     length)
                except IOError as e:
                    last = e
                    if self.path is None:
                        raise
            raise IOError(
                f"all replicas failed for block {blk['block_id']} "
                f"after {retries} location refetches: {last}")

    def _refetch_locations(self, blk: dict) -> None:
        fresh = self.client.nn.call("get_block_locations", self.path)
        for nb in fresh:
            if nb["block_id"] == blk["block_id"]:
                blk["locations"] = nb["locations"]
                self.client._remember_access(nb["block_id"],
                                             nb.get("access"))
                return
        raise IOError(f"block {blk['block_id']} no longer part of "
                      f"{self.path} after location refetch")

    def _read_replica_traced(self, blk: dict, offset: int,
                             length: int) -> bytes:
        last_err: Exception | None = None
        chunk = int(self.client._conf_get("tdfs.client.read.chunk.bytes",
                                          1 << 20))
        depth = max(1, int(self.client._conf_get(
            "tdfs.client.read.pipeline.depth", 4)))
        wire = self.client._read_wire
        for addr in blk["locations"]:
            try:
                data = self._read_one_replica(addr, blk["block_id"],
                                              offset, length, chunk,
                                              depth, wire)
                return data
            except Exception as e:  # noqa: BLE001 — dead/corrupt replica
                last_err = e
                if "checksum" in str(e).lower():
                    # tell the NameNode so it drops the corrupt replica
                    # and re-replicates (≈ ClientProtocol.reportBadBlocks)
                    try:
                        self.client.nn.call("report_bad_block",
                                            blk["block_id"], addr)
                    except Exception:  # noqa: BLE001 — best effort
                        pass
                continue
        raise IOError(f"all replicas failed for block {blk['block_id']} "
                      f"(locations {blk['locations']}): {last_err}")

    def _read_one_replica(self, addr: str, bid: int, offset: int,
                          length: int, chunk: int, depth: int,
                          wire: str) -> bytes:
        """Streamed read off ONE replica (≈ BlockSender), pipelined:
        chunk offsets are deterministic, so up to ``depth`` requests are
        kept on the wire while responses drain FIFO. Each response must
        return EXACTLY the bytes asked (the request offsets were
        computed assuming so) — a short/empty chunk fails the replica
        and the caller fails over. The pooled lease is exclusive for
        the window; any error releases it dead (in-flight responses
        would desync the next leaseholder)."""
        spans = [(offset + lo, min(chunk, length - lo))
                 for lo in range(0, length, chunk)]
        cli = self.client._dn_pool.acquire(addr)
        try:
            parts: list[bytes] = []
            sent = 0
            for done in range(len(spans)):
                while sent < len(spans) and sent - done < depth:
                    off, n = spans[sent]
                    cli.call_begin("read_block_chunk", bid, off, n, wire)
                    sent += 1
                r = cli.call_finish()
                data = r["data"]
                if "wire" in r:
                    data = _compress.get_codec(r["wire"]).decompress(
                        bytes(data))
                if len(data) != spans[done][1]:
                    raise IOError(
                        f"short read at {spans[done][0]} of block "
                        f"{bid}: got {len(data)}/{spans[done][1]} "
                        f"(total {r.get('total')})")
                parts.append(data)
        except BaseException:
            self.client._dn_pool.release(addr, cli, dead=True)
            raise
        self.client._dn_pool.release(addr, cli)
        return b"".join(parts)
