"""SecondaryNameNode — periodic offline checkpoint merge.

≈ ``org.apache.hadoop.hdfs.server.namenode.SecondaryNameNode``
(reference: SecondaryNameNode.java:64, 677 LoC): fetch the image + edits
from the NameNode, merge them into a fresh image in its own checkpoint dir,
and upload the result so the primary can truncate its journal. Transport is
the framework RPC (the reference used HTTP GET/PUT of the files)."""

from __future__ import annotations

import os
import threading
from typing import Any

from tpumr.dfs.editlog import IMAGE_NAME, FSEditLog, FSImage
from tpumr.ipc.rpc import RpcClient


class SecondaryNameNode:
    def __init__(self, nn_host: str, nn_port: int, checkpoint_dir: str,
                 conf: Any = None) -> None:
        from tpumr.security import rpc_secret
        self.nn = RpcClient(nn_host, nn_port, secret=rpc_secret(conf))
        self.dir = checkpoint_dir
        self.interval_s = float(conf.get("fs.checkpoint.period", 3600)
                                if conf is not None else 3600)
        os.makedirs(checkpoint_dir, exist_ok=True)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def do_checkpoint(self) -> None:
        """One checkpoint cycle (≈ SecondaryNameNode.doCheckpoint). The
        segments arrive as a list and are written as separate files so
        replay keeps per-segment torn-tail recovery; the NN's fetch token
        is echoed with the upload (≈ CheckpointSignature) so a superseded
        cycle is refused instead of purging uncovered edits."""
        state = self.nn.call("get_name_state")
        # clear any previous cycle's files, then mirror the NN layout
        for name in os.listdir(self.dir):
            if name.startswith("edits") or name == IMAGE_NAME:
                os.remove(os.path.join(self.dir, name))
        with open(os.path.join(self.dir, IMAGE_NAME), "wb") as f:
            f.write(state["image"])
        for i, seg in enumerate(state["segments"], start=1):
            with open(os.path.join(self.dir, f"edits-{i:010d}.jsonl"),
                      "wb") as f:
                f.write(seg)
        # offline merge using the namesystem's own replay function
        from tpumr.dfs.namenode import FSNamesystem
        namespace, counters = FSImage.load(self.dir)
        for op in FSEditLog.replay(self.dir):
            FSNamesystem.apply_op(namespace, counters, op)
        FSImage.save(self.dir, namespace, counters)
        with open(os.path.join(self.dir, IMAGE_NAME), "rb") as f:
            merged = f.read()
        self.nn.call("put_image", merged, state["token"])

    def start(self) -> "SecondaryNameNode":
        self._thread = threading.Thread(target=self._loop,
                                        name="secondary-nn", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.do_checkpoint()
            except Exception:  # noqa: BLE001 — retry next period
                pass

    def stop(self) -> None:
        self._stop.set()
