"""FileSystem SPI binding for tdfs:// URIs.

≈ ``org.apache.hadoop.hdfs.DistributedFileSystem`` (reference: hdfs/
DistributedFileSystem.java): the thin adapter from the FS contract to the
DFSClient, including block-location hints that drive locality-aware split
placement (FileInputFormat.getSplits → JobInProgress host caches)."""

from __future__ import annotations

from typing import Any, BinaryIO

from tpumr.dfs.client import DFSClient
from tpumr.fs.filesystem import (BlockLocation, FileStatus, FileSystem,
                                 Path)


class DistributedFileSystem(FileSystem):
    scheme = "tdfs"

    def __init__(self, conf: Any = None, authority: str = "") -> None:
        if not authority and conf is not None:
            authority = Path(conf.get("fs.default.name") or "").authority
        if not authority:
            raise ValueError("tdfs URI needs an authority (tdfs://host:port/)")
        host, port = authority.rsplit(":", 1)
        self.client = DFSClient(host, int(port), conf)
        self.authority = authority

    def _p(self, path: "str | Path") -> str:
        return Path(path).path

    def _q(self, path: str) -> Path:
        return Path(f"tdfs://{self.authority}{path}")

    def open(self, path: "str | Path") -> BinaryIO:
        return self.client.open(self._p(path))

    def create(self, path: "str | Path", overwrite: bool = True) -> BinaryIO:
        return self.client.create(self._p(path), overwrite=overwrite)

    def append(self, path: "str | Path") -> BinaryIO:
        """Block-granular append (≈ DistributedFileSystem.append with
        dfs.support.append): new data lands in new blocks; the stream's
        ``hflush()`` publishes mid-write. See docs/OPERATIONS.md for the
        divergence from the reference's within-block append."""
        return self.client.append(self._p(path))

    def exists(self, path: "str | Path") -> bool:
        return self.client.exists(self._p(path))

    def get_status(self, path: "str | Path") -> FileStatus:
        st = self.client.get_status(self._p(path))
        return FileStatus(path=self._q(st["path"]), length=st["length"],
                          is_dir=st["is_dir"],
                          replication=st.get("replication", 1),
                          block_size=st.get("block_size", 0),
                          mtime=st.get("mtime", 0.0),
                          owner=st.get("owner", ""))

    def get_permission(self, path: "str | Path") -> int:
        """Octal mode bits (distcp -p reads these to preserve them)."""
        return int(self.client.get_status(self._p(path)).get("mode", 0o644))

    def list_status(self, path: "str | Path") -> list[FileStatus]:
        return [FileStatus(path=self._q(st["path"]), length=st["length"],
                           is_dir=st["is_dir"],
                           replication=st.get("replication", 1),
                           block_size=st.get("block_size", 0),
                           mtime=st.get("mtime", 0.0),
                           owner=st.get("owner", ""))
                for st in self.client.list_status(self._p(path))]

    def mkdirs(self, path: "str | Path") -> bool:
        return self.client.mkdirs(self._p(path))

    def delete(self, path: "str | Path", recursive: bool = False) -> bool:
        return self.client.delete(self._p(path), recursive)

    def rename(self, src: "str | Path", dst: "str | Path") -> bool:
        return self.client.rename(self._p(src), self._p(dst))

    def set_permission(self, path: "str | Path", mode: int) -> None:
        self.client.set_permission(self._p(path), mode)

    def set_owner(self, path: "str | Path", owner: "str | None" = None,
                  group: "str | None" = None) -> None:
        self.client.set_owner(self._p(path), owner, group)

    def fsck(self, path: "str | Path" = "/") -> dict:
        return self.client.fsck(self._p(path))

    def set_replication(self, path: "str | Path", replication: int) -> bool:
        return self.client.set_replication(self._p(path), replication)

    def datanode_report(self) -> list[dict]:
        return self.client.datanode_report()

    def get_block_locations(self, path: "str | Path", offset: int,
                            length: int) -> list[BlockLocation]:
        blocks = self.client.nn.call("get_block_locations", self._p(path))
        out: list[BlockLocation] = []
        pos = 0
        for blk in blocks:
            size = blk["size"]
            if pos + size > offset and pos < offset + length:
                hosts = [a.rsplit(":", 1)[0] for a in blk["locations"]]
                out.append(BlockLocation(hosts, pos, size))
            pos += size
        return out


FileSystem.register("tdfs", DistributedFileSystem)
