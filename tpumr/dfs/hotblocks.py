"""Hot-block heavy hitters: bounded SpaceSaving sketches on datanodes,
folded into one cluster-wide table on the namenode.

Millions of users hammer the same inputs — the devcache/replication
policies the roadmap points at need to know WHICH blocks are hot, but
counting every block read exactly would cost O(blocks) memory on a
datanode that serves arbitrarily many. SpaceSaving (Metwally et al.,
"Efficient Computation of Frequent and Top-k Elements in Data Streams")
keeps exactly ``k`` counters and guarantees any block whose true count
exceeds N/k is present, with per-entry overestimation bounded by the
recorded ``err`` field. Datanodes piggyback their top entries on the
heartbeats they already send; the namenode replaces (not accumulates)
each datanode's slice, so a re-delivered heartbeat folds idempotently
and a dead datanode's contribution vanishes with it.
"""

from __future__ import annotations

import threading
from typing import Any


class SpaceSaving:
    """Bounded top-K counter sketch (at most ``k`` tracked keys).

    ``offer(key)``: if tracked, increment; else if there is room, admit
    at count 1; else evict the current minimum and inherit its count
    (the classic SpaceSaving replacement), recording that minimum as
    the new entry's error bound. Estimates never undercount:
    ``count - err <= true <= count``.
    """

    def __init__(self, k: int = 64) -> None:
        self.k = max(1, int(k))
        #: key -> [count, err]; counts are ints until decay() ages
        #: them fractional (wire folds re-truncate at the boundary)
        self._counts: "dict[str, list[float]]" = {}
        self.total = 0.0   # every offer, tracked or not

    def __len__(self) -> int:
        return len(self._counts)

    def offer(self, key: str, by: int = 1) -> None:
        self.total += by
        ent = self._counts.get(key)
        if ent is not None:
            ent[0] += by
            return
        if len(self._counts) < self.k:
            self._counts[key] = [by, 0]
            return
        victim = min(self._counts, key=lambda x: self._counts[x][0])
        floor = self._counts.pop(victim)[0]
        self._counts[key] = [floor + by, floor]

    def decay(self, factor: float) -> None:
        """Exponentially age every count (and ``total``) by ``factor``
        in [0,1]; entries that decay below one count are dropped. The
        datanode applies this each heartbeat so the sketch tracks the
        CURRENT read mix — without it, yesterday's hot block keeps its
        replica boost forever and the namenode's cool-down never fires.
        Counts go fractional on purpose: truncating to int would turn a
        gentle per-heartbeat factor into a flat -1/heartbeat for every
        small count (int(15 * 0.99) = 14), emptying the sketch orders
        of magnitude faster than the configured half-life."""
        if factor >= 1.0:
            return
        factor = max(0.0, factor)
        for key in list(self._counts):
            ent = self._counts[key]
            ent[0] *= factor
            ent[1] *= factor
            if ent[0] < 1.0:
                del self._counts[key]
        self.total *= factor

    def estimate(self, key: str) -> int:
        ent = self._counts.get(key)
        return ent[0] if ent else 0

    def topk(self, n: "int | None" = None) -> "list[tuple[str, int, int]]":
        """(key, count, err) rows, highest count first."""
        rows = sorted(((key, ent[0], ent[1])
                       for key, ent in self._counts.items()),
                      key=lambda r: (-r[1], r[0]))
        return rows if n is None else rows[:n]

    def to_wire(self, n: "int | None" = None) -> dict:
        """JSON-safe snapshot for heartbeat piggybacking."""
        return {"total": self.total,
                "top": [list(r) for r in self.topk(n)]}

    @staticmethod
    def from_wire(doc: dict) -> "SpaceSaving":
        sk = SpaceSaving(k=max(1, len(doc.get("top", [])) or 1))
        sk.k = max(sk.k, len(doc.get("top", [])))
        for key, count, err in doc.get("top", []):
            sk._counts[str(key)] = [int(count), int(err)]
        sk.total = int(doc.get("total", 0))
        return sk

    def merge(self, other: "SpaceSaving") -> "SpaceSaving":
        """Fold another sketch in (union of streams). Counts add for
        shared keys; the result is re-truncated to this sketch's ``k``
        keeping the largest, so memory stays bounded after any number
        of merges. Error bounds add conservatively."""
        for key, (count, err) in other._counts.items():
            ent = self._counts.get(key)
            if ent is not None:
                ent[0] += count
                ent[1] += err
            else:
                self._counts[key] = [count, err]
        self.total += other.total
        if len(self._counts) > self.k:
            keep = self.topk(self.k)
            self._counts = {key: [count, err] for key, count, err in keep}
        return self


class HotBlockTable:
    """Cluster-wide hot-block view: one sketch slice per datanode,
    replaced wholesale on every heartbeat (idempotent fold), merged on
    demand for ``/hotblocks`` and ``get_hot_blocks``. Thread-safe; its
    own leaf lock is only ever held for dict ops, never while calling
    out."""

    def __init__(self, k: int = 64) -> None:
        self.k = max(1, int(k))
        self._mu = threading.Lock()
        self._per_dn: "dict[str, dict]" = {}   # addr -> wire doc

    def fold(self, addr: str, doc: "dict | None") -> None:
        if not doc:
            return
        with self._mu:
            self._per_dn[addr] = doc

    def drop(self, addr: str) -> None:
        """A dead datanode's reads stop counting the moment it does."""
        with self._mu:
            self._per_dn.pop(addr, None)

    def top(self, n: int = 16) -> "list[dict[str, Any]]":
        """Merged top-``n``: block_id, estimated cluster-wide reads,
        error bound, and which datanodes reported it."""
        with self._mu:
            slices = dict(self._per_dn)
        merged = SpaceSaving(k=self.k)
        reporters: "dict[str, list[str]]" = {}
        for addr, doc in sorted(slices.items()):
            merged.merge(SpaceSaving.from_wire(doc))
            for key, _count, _err in doc.get("top", []):
                reporters.setdefault(str(key), []).append(addr)
        return [{"block": key, "reads": count, "err": err,
                 "datanodes": reporters.get(key, [])}
                for key, count, err in merged.topk(n)]

    def total_reads(self) -> int:
        with self._mu:
            return sum(int(doc.get("total", 0))
                       for doc in self._per_dn.values())
