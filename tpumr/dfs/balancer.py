"""Balancer — iterative block rebalancing.

≈ ``org.apache.hadoop.hdfs.server.balancer.Balancer`` (reference:
Balancer.java, 1642 LoC): compute mean utilization, classify nodes as over-
or under-utilized against a threshold band, then move blocks from the
fullest nodes to the emptiest until every node is within the band or no
productive move remains. Moves copy replica data node→node and then retire
the source replica via the NameNode (≈ the balancer's DataTransferProtocol
copyBlock + NamenodeProtocol feedback loop)."""

from __future__ import annotations

from typing import Any

from tpumr.ipc.rpc import RpcClient


class Balancer:
    def __init__(self, nn_host: str, nn_port: int,
                 threshold: float = 0.10, conf: Any = None) -> None:
        from tpumr.security import rpc_secret
        self._secret = rpc_secret(conf)
        self.nn = RpcClient(nn_host, nn_port, secret=self._secret)
        self.threshold = threshold
        self._dn_clients: dict[str, RpcClient] = {}

    def _dn(self, addr: str) -> RpcClient:
        cli = self._dn_clients.get(addr)
        if cli is None:
            host, port = addr.rsplit(":", 1)
            cli = self._dn_clients[addr] = RpcClient(host, int(port), secret=self._secret)
        return cli

    def _utilization(self) -> dict[str, float]:
        return {d["addr"]: d["used"] / max(1, d["capacity"])
                for d in self.nn.call("datanode_report")}

    def run_iteration(self, max_moves: int = 16) -> int:
        """One balancing pass; returns the number of blocks moved."""
        util = self._utilization()
        if not util:
            return 0
        avg = sum(util.values()) / len(util)
        over = sorted((a for a, u in util.items()
                       if u > avg + self.threshold),
                      key=lambda a: -util[a])
        under = sorted((a for a, u in util.items()
                        if u < avg - self.threshold),
                       key=lambda a: util[a])
        moves = 0
        for src in over:
            if moves >= max_moves or not under:
                break
            for blk in self.nn.call("get_blocks", src, max_moves):
                target = next((t for t in under
                               if t not in blk["locations"]), None)
                if target is None:
                    continue
                try:
                    data = self._dn(src).call("read_block", blk["block_id"],
                                              0, -1)
                    self._dn(target).call("write_block", blk["block_id"],
                                          data, [])
                    self.nn.call("remove_replica", src, blk["block_id"])
                    moves += 1
                except Exception:  # noqa: BLE001 — skip failed move
                    continue
                if moves >= max_moves:
                    break
        return moves

    def balance(self, max_iterations: int = 10) -> int:
        """Run until balanced or no iteration makes progress
        (≈ Balancer.run's convergence loop)."""
        total = 0
        for _ in range(max_iterations):
            moved = self.run_iteration()
            total += moved
            if moved == 0:
                break
        return total
