"""Striped namespace locking for the NameNode.

PR 17's bench rig showed the namesystem saturating at 32 clients with
~0.6 of op p99 spent queueing on the ONE ``namespace`` RLock — every
stat, read, create and datanode heartbeat serialized behind every
other op's editlog fsync. This module replays the master's lock
decomposition (PR 8) on the DFS control plane with THREE classes, all
slotted into the repo-wide rank table (tpumr/metrics/locks.py):

- ``namespace`` (rank 25) — the structural/global lock, held only for
  cross-stripe ops: anything touching a SHALLOW path (fewer components
  than the stripe depth, e.g. ``/user`` itself), fsck, checkpoints.
  A structural op additionally acquires every stripe, so it excludes
  all striped ops without those ops ever taking the global lock.
- ``namespace-s<i>`` stripes (rank 26) — partition the path tree by a
  stable hash of the first ``depth`` path components. An op on
  ``/user/alice/f`` locks only alice's stripe; ops in other stripes
  (other users' writes, the shared input tree's reads) proceed in
  parallel, each paying only its OWN editlog group-commit wait.
  Equal-rank acquisition is legal by the rank rule, so multi-path ops
  (rename) take the union of their stripe sets in ascending stripe
  index — a global total order that makes stripe deadlocks impossible.
- ``namespace-blocks`` (rank 27) — the block/datanode plane: location
  maps, datanode liveness, pending commands, leases, safemode
  accounting. Short critical sections that NEVER journal, so datanode
  heartbeats and block reports stop queueing behind namespace fsyncs
  entirely. Ordering: stripe (26) -> blocks (27) is legal; the
  reverse is a rank violation the debug assertion catches.

Subtree coverage argument: a striped op's lock is the stripe of its
path's first-``depth`` components. Every descendant of a path with
>= depth components shares that prefix, hence that stripe — so a
subtree delete/rename under its stripe excludes every op on every
path inside the subtree. Paths with FEWER than depth components fall
back to structural, which excludes everything.
"""

from __future__ import annotations

import contextlib
import threading
import zlib
from typing import Any, Iterator

from tpumr.metrics.locks import (ORDER_CHECK, RANK_NAMESPACE,
                                 RANK_NAMESPACE_BLOCKS,
                                 RANK_NAMESPACE_STRIPE, InstrumentedRLock)


class NamespaceLocks:
    """The NameNode's three lock classes plus the stripe map.

    Thread-local frames record which stripes the current thread holds
    so (a) ``covers()`` lets _ensure_parents refuse to create an inode
    outside the held stripe set (a racy fallback that would otherwise
    silently bypass striping) and (b) nested striped contexts that
    would acquire OUTSIDE the held set — an ordering hazard the rank
    table cannot see because stripes share a rank — fail fast under
    the same debug switch as the rank assertion."""

    def __init__(self, stripes: int = 8, depth: int = 2) -> None:
        self.n = max(1, int(stripes))
        self.depth = max(1, int(depth))
        self.global_lock = InstrumentedRLock(name="namespace",
                                             rank=RANK_NAMESPACE)
        self.stripes = [
            InstrumentedRLock(name=f"namespace-s{i}",
                              rank=RANK_NAMESPACE_STRIPE)
            for i in range(self.n)]
        self.blocks = InstrumentedRLock(name="namespace-blocks",
                                        rank=RANK_NAMESPACE_BLOCKS)
        self._all = frozenset(range(self.n))
        self._tl = threading.local()

    # ------------------------------------------------------------ map

    def stripe_index(self, path: str) -> "int | None":
        """Stripe owning ``path``, or None when the path is too shallow
        to stripe (structural territory). Stable hash — must not vary
        across processes/restarts the way ``hash()`` does."""
        parts = [p for p in path.split("/") if p]
        if len(parts) < self.depth:
            return None
        key = "/".join(parts[:self.depth])
        return zlib.crc32(key.encode()) % self.n

    # ------------------------------------------------------------ frames

    def _frames(self) -> "list[frozenset]":
        f = getattr(self._tl, "frames", None)
        if f is None:
            f = self._tl.frames = []
        return f

    def held_set(self) -> frozenset:
        """Union of stripe indices held by this thread."""
        out: frozenset = frozenset()
        for f in self._frames():
            out |= f
        return out

    def structural_held(self) -> bool:
        return any(f is self._all or f == self._all
                   for f in self._frames())

    def covers(self, path: str) -> bool:
        """Does this thread hold locks excluding all ops on ``path``?"""
        if self.structural_held():
            return True
        i = self.stripe_index(path)
        return i is not None and i in self.held_set()

    # ------------------------------------------------------------ contexts

    @contextlib.contextmanager
    def for_paths(self, *paths: str) -> Iterator[None]:
        """Lock context for an op touching exactly ``paths`` (and, for
        subtree ops, everything under them). Escalates to structural
        when any path is too shallow to stripe."""
        idxs: "set[int]" = set()
        for p in paths:
            i = self.stripe_index(p)
            if i is None:
                # shallow path: escalate. Guarded in structural() — a
                # thread already inside a striped frame must NOT widen
                # to structural (global rank 25 after stripe rank 26
                # deadlocks against a concurrent structural op, and the
                # widening check below can't see this branch)
                with self.structural():
                    yield
                return
            idxs.add(i)
        order = sorted(idxs)
        frames = self._frames()
        if ORDER_CHECK and frames and not self.structural_held() \
                and not idxs <= self.held_set():
            # stripes share a rank, so the rank assertion cannot catch
            # two threads acquiring overlapping stripe sets in opposite
            # orders; forbid widening a held striped context instead
            raise AssertionError(
                f"nested stripe acquisition outside held set: "
                f"want {order}, hold {sorted(self.held_set())}")
        for i in order:
            self.stripes[i].acquire()
        frames.append(frozenset(idxs))
        try:
            yield
        finally:
            frames.pop()
            for i in reversed(order):
                self.stripes[i].release()

    @contextlib.contextmanager
    def structural(self) -> Iterator[None]:
        """Global + every stripe, ascending — excludes all namespace
        ops. Keep these sections short; every striped op queues."""
        frames = self._frames()
        if frames and not self.structural_held():
            # escalating from a held STRIPED frame acquires the global
            # lock after a stripe — the reverse of every other thread's
            # order. Under concurrent load (trace replay) that deadlocks
            # against an in-flight structural op: A holds stripe s and
            # wants global, B holds global and wants s. The rank
            # assertion only fires under ORDER_CHECK; production would
            # hang, so this is a hard error either way. Callers must
            # decide structural-vs-striped BEFORE acquiring anything
            # (see FSNamesystem._locked's lock-free pre-check).
            raise RuntimeError(
                "structural escalation while holding stripes "
                f"{sorted(self.held_set())} — decide escalation before "
                "acquiring any stripe")
        self.global_lock.acquire()
        for lk in self.stripes:
            lk.acquire()
        frames = self._frames()
        frames.append(self._all)
        try:
            yield
        finally:
            frames.pop()
            for lk in reversed(self.stripes):
                lk.release()
            self.global_lock.release()

    # ------------------------------------------------------------ metrics

    def bind_metrics(self, reg: Any) -> None:
        """One wait/hold family per lock CLASS (stripes share a pair —
        per-stripe series would be 2·n mostly-idle histograms nobody
        graphs; the class aggregate is what the bench SLO reads)."""
        self.global_lock.bind(
            reg.histogram("nn_lock_wait_seconds|lock=namespace"),
            reg.histogram("nn_lock_hold_seconds|lock=namespace"))
        sw = reg.histogram("nn_lock_wait_seconds|lock=namespace-stripe")
        sh = reg.histogram("nn_lock_hold_seconds|lock=namespace-stripe")
        for lk in self.stripes:
            lk.bind(sw, sh)
        self.blocks.bind(
            reg.histogram("nn_lock_wait_seconds|lock=namespace-blocks"),
            reg.histogram("nn_lock_hold_seconds|lock=namespace-blocks"))
