"""MiniDFSCluster — NameNode + N DataNodes in one process.

≈ ``org.apache.hadoop.hdfs.MiniDFSCluster`` (reference: src/test/org/apache/
hadoop/hdfs/MiniDFSCluster.java): real RPC over localhost ports, real
heartbeats and block reports, per-node storage dirs under a temp root —
multi-node DFS semantics without a real cluster (SURVEY.md §4.2)."""

from __future__ import annotations

import shutil
import tempfile
import time
from typing import Any

from tpumr.dfs.client import DFSClient
from tpumr.dfs.datanode import DataNode
from tpumr.dfs.namenode import NameNode
from tpumr.mapred.jobconf import JobConf


class MiniDFSCluster:
    def __init__(self, num_datanodes: int = 3, conf: Any = None,
                 root: str | None = None) -> None:
        self.conf = conf or JobConf()
        # mini clusters default to a fast heartbeat (tests wait on
        # liveness); an explicit site value still wins
        self.conf.set_if_unset("tdfs.datanode.heartbeat.s", 0.2)
        self.root = root or tempfile.mkdtemp(prefix="tpumr-minidfs-")
        self._own_root = root is None
        self.namenode = NameNode(f"{self.root}/name", self.conf).start()
        host, port = self.namenode.address
        self.nn_host, self.nn_port = host, port
        self.datanodes = []
        for i in range(num_datanodes):
            dn = DataNode(host, port, f"{self.root}/data{i}", self.conf)
            dn.fi_index = i   # the d<n> of the dn.crash.d<n> chaos seam
            self.datanodes.append(dn.start())
        self._wait_active(num_datanodes)

    def _wait_active(self, n: int, timeout: float = 20.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.namenode.ns.datanodes) >= n \
                    and not self.namenode.ns.safemode:
                return
            time.sleep(0.05)
        raise TimeoutError("MiniDFSCluster did not become active")

    @property
    def uri(self) -> str:
        return f"tdfs://{self.nn_host}:{self.nn_port}"

    def client(self) -> DFSClient:
        return DFSClient(self.nn_host, self.nn_port, self.conf)

    def restart_namenode(self, clean: bool = True) -> None:
        """Stop + start the NameNode over the same name dir (tests the
        image/edits recovery path + safemode). ``clean=False`` kills
        instead (no editlog close — the crash-recovery path)."""
        if clean:
            self.namenode.stop()
        else:
            self.namenode.kill()
        time.sleep(0.1)
        self.namenode = self._bind_namenode()

    def kill_namenode(self) -> None:
        """SIGKILL-equivalent on the NameNode, WITHOUT restarting it —
        the chaos window where clients ride their RPC retry policy.
        Call restart_killed_namenode() to bring it back on the port."""
        self.namenode.kill()

    def restart_killed_namenode(self) -> NameNode:
        """Bring a killed NameNode back on the same port (editlog
        replay + safemode until enough block reports arrive)."""
        self.namenode = self._bind_namenode()
        return self.namenode

    def _bind_namenode(self) -> NameNode:
        # the dying server's socket may linger briefly: retry the bind
        # on the SAME port so clients' cached addresses stay valid
        # (the master_restart rebind idiom)
        last: Exception | None = None
        for _ in range(250):
            try:
                return NameNode(f"{self.root}/name", self.conf,
                                port=self.nn_port).start()
            except OSError as e:
                last = e
                time.sleep(0.02)
        raise OSError(f"could not rebind NameNode on port "
                      f"{self.nn_port}: {last}")

    def stop_datanode(self, i: int) -> DataNode:
        dn = self.datanodes[i]
        dn.stop()
        return dn

    def kill_datanode(self, i: int) -> DataNode:
        """Hard-kill datanode ``i`` mid-whatever (no deregistration);
        its storage dir survives for a later rejoin."""
        dn = self.datanodes[i]
        dn.kill()
        return dn

    def restart_datanode(self, i: int) -> DataNode:
        """Cold-restart datanode ``i`` over its old storage dir: a new
        process image that re-registers and block-reports its surviving
        replicas (the dn churn rejoin path)."""
        old = self.datanodes[i]
        if not old.killed:
            old.stop()
        dn = DataNode(self.nn_host, self.nn_port,
                      f"{self.root}/data{i}", self.conf)
        dn.fi_index = i
        self.datanodes[i] = dn.start()
        return self.datanodes[i]

    def shutdown(self) -> None:
        for dn in self.datanodes:
            dn.stop()
        self.namenode.stop()
        if self._own_root:
            shutil.rmtree(self.root, ignore_errors=True)

    def __enter__(self) -> "MiniDFSCluster":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.shutdown()
