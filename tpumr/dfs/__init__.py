"""tdfs — the replicated block store (DFS).

≈ the reference's HDFS layer (src/hdfs/org/apache/hadoop/hdfs/, 53k LoC Java
— SURVEY.md §2.3), re-designed small: a NameNode (namespace + block map +
leases + replication monitor + safemode, journaled by an edit log with
image checkpoints), DataNodes (checksummed block files, heartbeats, block
reports, pipelined writes), a DFSClient (write pipeline with failover,
replica-failover reads), a FileSystem SPI binding (scheme ``tdfs://``), a
Balancer, and a MiniDFSCluster test harness.

Design notes vs the reference: block transfer rides the framework RPC codec
(one hop per pipeline stage) instead of a bespoke streaming protocol;
metadata ops journal JSON lines instead of binary FSEditLog records. The
*contracts* — single-writer leases, write pipeline, block reports rebuilding
locations, safemode until block threshold, re-replication on DataNode death,
checkpoint = image + replayed edits — are the reference's.
"""

from tpumr.dfs.client import DFSClient
from tpumr.dfs.datanode import DataNode
from tpumr.dfs.namenode import NameNode
from tpumr.dfs.dfs_filesystem import DistributedFileSystem
from tpumr.dfs.mini_cluster import MiniDFSCluster

__all__ = ["DFSClient", "DataNode", "NameNode", "DistributedFileSystem",
           "MiniDFSCluster"]
