"""FsShell — the ``tpumr fs`` command-line file-system client.

≈ the reference's ``org.apache.hadoop.fs.FsShell`` (hadoop-1.0.3
``src/core/org/apache/hadoop/fs/FsShell.java``): dash-prefixed subcommands
(``-ls``, ``-put``, ``-cat``, …) resolved against the FileSystem SPI, so
the same shell drives ``file://``, ``mem://`` and ``tdfs://`` URIs.
Glob expansion mirrors FsShell's use of ``FileSystem.globStatus``.
"""

from __future__ import annotations

import sys
import time
from typing import Any, Callable

from tpumr.fs.filesystem import FileStatus, FileSystem, Path, get_filesystem


class ShellError(Exception):
    pass


class FsShell:
    """Each ``cmd_*`` method is one dash-command; ``run`` dispatches."""

    def __init__(self, conf: Any = None, default_fs: str | None = None,
                 out: Any = None, err: Any = None) -> None:
        self.conf = conf
        self.default_fs = default_fs
        self.out = out or sys.stdout
        self.err = err or sys.stderr

    # ------------------------------------------------------------ helpers

    def _resolve(self, path: str) -> str:
        if "://" in path:
            return path
        if self.default_fs:
            scheme, _, rest = self.default_fs.partition("://")
            authority = rest.split("/", 1)[0]
            if not path.startswith("/"):
                path = "/" + path
            return f"{scheme}://{authority}{path}"
        return path

    def _fs(self, path: str) -> FileSystem:
        return get_filesystem(self._resolve(path), self.conf)

    def _expand(self, pattern: str) -> list[FileStatus]:
        """Glob-expand one argument; error if it matches nothing."""
        full = self._resolve(pattern)
        fs = get_filesystem(full, self.conf)
        if any(c in full for c in "*?[{"):
            matches = fs.glob_status(full)
            if not matches:
                raise ShellError(f"{pattern}: No such file or directory")
            return matches
        if not fs.exists(full):
            raise ShellError(f"{pattern}: No such file or directory")
        return [fs.get_status(full)]

    def _print(self, *a: Any) -> None:
        print(*a, file=self.out)

    # ------------------------------------------------------------ commands

    def cmd_ls(self, *args: str) -> int:
        recursive = False
        paths = [a for a in args if a != "-R"]
        recursive = len(paths) != len(args)
        for p in paths or ["/"]:
            for st in self._expand(p):
                fs = self._fs(p)
                items = ([st] if not st.is_dir
                         else fs.list_status(st.path))
                self._print(f"Found {len(items)} items") if st.is_dir else None
                self._ls_items(fs, items, recursive)
        return 0

    def _ls_items(self, fs: FileSystem, items: list[FileStatus],
                  recursive: bool) -> None:
        for it in sorted(items, key=lambda s: str(s.path)):
            kind = "d" if it.is_dir else "-"
            mtime = time.strftime("%Y-%m-%d %H:%M",
                                  time.localtime(it.mtime or 0))
            repl = getattr(it, "replication", 1) or 1
            self._print(f"{kind}rw-r--r--  {repl:>2} {it.length:>12} "
                        f"{mtime} {it.path}")
            if recursive and it.is_dir:
                self._ls_items(fs, fs.list_status(it.path), True)

    def cmd_lsr(self, *args: str) -> int:
        return self.cmd_ls("-R", *args)

    def cmd_mkdir(self, *args: str) -> int:
        if not args:
            raise ShellError("-mkdir: missing path")
        for p in args:
            self._fs(p).mkdirs(self._resolve(p))
        return 0

    def cmd_touchz(self, *args: str) -> int:
        for p in args:
            full = self._resolve(p)
            with self._fs(p).create(full) as f:
                f.write(b"")
        return 0

    def cmd_cat(self, *args: str) -> int:
        for p in args:
            for st in self._expand(p):
                if st.is_dir:
                    raise ShellError(f"{st.path}: is a directory")
                data = get_filesystem(st.path, self.conf).read_bytes(st.path)
                self.out.write(data.decode("utf-8", errors="replace"))
        return 0

    def cmd_text(self, *args: str) -> int:
        """≈ FsShell -text: decodes SequenceFiles, else plain cat."""
        from tpumr.io import sequencefile
        for p in args:
            for st in self._expand(p):
                fs = get_filesystem(st.path, self.conf)
                with fs.open(st.path) as f:
                    head = f.read(len(sequencefile.MAGIC))
                if head == sequencefile.MAGIC:
                    with fs.open(st.path) as f:
                        for k, v in sequencefile.Reader(f):
                            self._print(f"{k}\t{v}")
                else:
                    self.out.write(fs.read_bytes(st.path)
                                   .decode("utf-8", errors="replace"))
        return 0

    def cmd_tail(self, *args: str) -> int:
        for p in args:
            st = self._expand(p)[0]
            fs = get_filesystem(st.path, self.conf)
            with fs.open(st.path) as f:
                if st.length > 1024:
                    f.seek(st.length - 1024)
                data = f.read()
            self.out.write(data.decode("utf-8", errors="replace"))
        return 0

    def cmd_put(self, *args: str) -> int:
        if len(args) < 2:
            raise ShellError("-put: <localsrc...> <dst>")
        *srcs, dst = args
        import os
        dst_full = self._resolve(dst)
        dst_fs = get_filesystem(dst_full, self.conf)
        many = len(srcs) > 1 or (dst_fs.exists(dst_full)
                                 and dst_fs.get_status(dst_full).is_dir)
        for src in srcs:
            with open(src, "rb") as f:
                data = f.read()
            target = (str(Path(dst_full).child(os.path.basename(src)))
                      if many else dst_full)
            dst_fs.write_bytes(target, data)
        return 0

    cmd_copyFromLocal = cmd_put

    def cmd_get(self, *args: str) -> int:
        if len(args) < 2:
            raise ShellError("-get: <src...> <localdst>")
        *srcs, dst = args
        import os
        matches = [st for s in srcs for st in self._expand(s)]
        if len(matches) > 1 and not os.path.isdir(dst):
            raise ShellError(f"-get: {len(matches)} sources but {dst} "
                             "is not a directory")
        for st in matches:
            data = get_filesystem(st.path, self.conf).read_bytes(st.path)
            target = (os.path.join(dst, st.path.name)
                      if os.path.isdir(dst) else dst)
            with open(target, "wb") as f:
                f.write(data)
        return 0

    cmd_copyToLocal = cmd_get

    def cmd_cp(self, *args: str) -> int:
        if len(args) != 2:
            raise ShellError("-cp: <src> <dst>")
        src, dst = self._resolve(args[0]), self._resolve(args[1])
        sfs, dfs = get_filesystem(src, self.conf), get_filesystem(dst, self.conf)
        dfs.write_bytes(dst, sfs.read_bytes(src))
        return 0

    def cmd_mv(self, *args: str) -> int:
        if len(args) != 2:
            raise ShellError("-mv: <src> <dst>")
        src, dst = self._resolve(args[0]), self._resolve(args[1])
        if not self._fs(args[0]).rename(src, dst):
            raise ShellError(f"-mv failed: {src} -> {dst}")
        return 0

    def _delete_or_trash(self, st, recursive: bool,
                         skip_trash: bool) -> None:
        """fs.trash.interval > 0 routes deletes into the per-user trash
        (≈ FsShell delete → Trash.moveToTrash); -skipTrash bypasses."""
        fs = get_filesystem(st.path, self.conf)
        if not skip_trash:
            from tpumr.fs.trash import Trash
            trash = Trash(fs, self.conf)
            if trash.enabled and trash.move_to_trash(st.path):
                self._print(f"Moved to trash: {st.path}")
                return
        fs.delete(st.path, recursive=recursive)
        self._print(f"Deleted {st.path}")

    def cmd_rm(self, *args: str) -> int:
        skip = "-skipTrash" in args
        for p in (a for a in args if a != "-skipTrash"):
            for st in self._expand(p):
                if st.is_dir:
                    raise ShellError(f"{st.path}: is a directory (use -rmr)")
                self._delete_or_trash(st, recursive=False, skip_trash=skip)
        return 0

    def cmd_rmr(self, *args: str) -> int:
        skip = "-skipTrash" in args
        for p in (a for a in args if a != "-skipTrash"):
            for st in self._expand(p):
                self._delete_or_trash(st, recursive=True, skip_trash=skip)
        return 0

    def cmd_expunge(self, *args: str) -> int:
        """Empty the caller's trash on the default fs (≈ -expunge)."""
        from tpumr.fs.trash import Trash
        base = self._resolve(args[0] if args else "/")
        fs = get_filesystem(base, self.conf)
        n = Trash(fs, self.conf).expunge_all()
        self._print(f"Expunged {n} trash checkpoint(s)")
        return 0

    def cmd_du(self, *args: str) -> int:
        for p in args or ["/"]:
            total = 0
            for st in self._expand(p):
                fs = get_filesystem(st.path, self.conf)
                for f in fs.list_files(st.path, recursive=True) \
                        if st.is_dir else [st]:
                    self._print(f"{f.length:<12} {f.path}")
                    total += f.length
            self._print(f"total {total}")
        return 0

    def cmd_dus(self, *args: str) -> int:
        for p in args or ["/"]:
            for st in self._expand(p):
                fs = get_filesystem(st.path, self.conf)
                total = (sum(f.length for f in
                             fs.list_files(st.path, recursive=True))
                         if st.is_dir else st.length)
                self._print(f"{st.path}\t{total}")
        return 0

    def cmd_count(self, *args: str) -> int:
        def walk(fs: FileSystem, st: FileStatus) -> tuple[int, int, int]:
            if not st.is_dir:
                return 0, 1, st.length
            ndirs, nfiles, nbytes = 1, 0, 0
            for child in fs.list_status(st.path):
                d, f, b = walk(fs, child)
                ndirs, nfiles, nbytes = ndirs + d, nfiles + f, nbytes + b
            return ndirs, nfiles, nbytes

        for p in args:
            for st in self._expand(p):
                fs = get_filesystem(st.path, self.conf)
                ndirs, nfiles, nbytes = walk(fs, st)
                self._print(f"{ndirs:>8} {nfiles:>8} {nbytes:>12} {st.path}")
        return 0

    def cmd_stat(self, *args: str) -> int:
        for p in args:
            st = self._expand(p)[0]
            self._print(time.strftime("%Y-%m-%d %H:%M:%S",
                                      time.localtime(st.mtime or 0)))
        return 0

    def cmd_test(self, *args: str) -> int:
        """-test -[ezd] <path>: exit 0/1 like the reference."""
        if len(args) != 2:
            raise ShellError("-test: -[ezd] <path>")
        flag, p = args
        full = self._resolve(p)
        fs = get_filesystem(full, self.conf)
        if flag == "-e":
            return 0 if fs.exists(full) else 1
        if not fs.exists(full):
            return 1
        st = fs.get_status(full)
        if flag == "-z":
            return 0 if st.length == 0 else 1
        if flag == "-d":
            return 0 if st.is_dir else 1
        raise ShellError(f"-test: unknown flag {flag}")

    def cmd_setrep(self, *args: str) -> int:
        """-setrep [-w] <rep> <path> (tdfs only; no-op elsewhere)."""
        args = [a for a in args if a != "-w"]
        if len(args) != 2:
            raise ShellError("-setrep: <rep> <path>")
        rep, p = int(args[0]), self._resolve(args[1])
        fs = get_filesystem(p, self.conf)
        set_rep = getattr(fs, "set_replication", None)
        if set_rep is not None:
            set_rep(p, rep)
            self._print(f"Replication {rep} set: {p}")
        return 0

    def cmd_chmod(self, *args: str) -> int:
        """-chmod <octal-mode> <path>... (≈ FsShell chmod; tdfs only)."""
        if len(args) < 2:
            raise ShellError("-chmod: <octal-mode> <path>...")
        try:
            mode = int(args[0], 8)
        except ValueError:
            raise ShellError(f"-chmod: bad mode {args[0]!r} "
                             "(octal, e.g. 750)") from None
        for p in args[1:]:
            full = self._resolve(p)
            fs = get_filesystem(full, self.conf)
            setp = getattr(fs, "set_permission", None)
            if setp is None:
                self._print("chmod: only meaningful on tdfs://")
                return 1
            setp(full, mode)
        return 0

    def cmd_chown(self, *args: str) -> int:
        """-chown <owner>[:<group>] <path>... (≈ FsShell chown; tdfs
        only)."""
        if len(args) < 2:
            raise ShellError("-chown: <owner>[:<group>] <path>...")
        owner, _, group = args[0].partition(":")
        for p in args[1:]:
            full = self._resolve(p)
            fs = get_filesystem(full, self.conf)
            seto = getattr(fs, "set_owner", None)
            if seto is None:
                self._print("chown: only meaningful on tdfs://")
                return 1
            seto(full, owner or None, group or None)
        return 0

    def cmd_df(self, *args: str) -> int:
        for p in args or ["/"]:
            fs = self._fs(p)
            report = getattr(fs, "datanode_report", None)
            if report is None:
                self._print("df: only meaningful on tdfs://")
                continue
            for dn in report():
                self._print(f"{dn['addr']}\tcapacity={dn['capacity']}"
                            f"\tused={dn['used']}")
        return 0

    # ------------------------------------------------------------ dispatch

    def run(self, argv: list[str]) -> int:
        if not argv:
            self._usage()
            return 255
        cmd, *rest = argv
        if not cmd.startswith("-"):
            self.err.write(f"fs: unknown command {cmd}\n")
            self._usage()
            return 255
        fn: Callable[..., int] | None = getattr(self, "cmd_" + cmd[1:], None)
        if fn is None:
            self.err.write(f"fs: unknown command {cmd}\n")
            self._usage()
            return 255
        try:
            return fn(*rest) or 0
        except ShellError as e:
            self.err.write(f"fs {cmd}: {e}\n")
            return 1
        except FileNotFoundError as e:
            self.err.write(f"fs {cmd}: {e}\n")
            return 1

    def _usage(self) -> None:
        cmds = sorted(m[4:] for m in dir(self) if m.startswith("cmd_"))
        self.err.write("Usage: tpumr fs [-fs <uri>] -<cmd> ...\nCommands: "
                       + " ".join("-" + c for c in cmds) + "\n")
