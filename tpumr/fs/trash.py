"""Trash — recoverable deletes with periodic expiry.

≈ ``org.apache.hadoop.fs.Trash`` (reference: src/core/org/apache/hadoop/
fs/Trash.java): when ``fs.trash.interval`` (minutes) is positive, shell
deletes MOVE paths into ``/user/<user>/.Trash/Current`` instead of
destroying them; a checkpoint renames ``Current`` to a timestamped dir,
and checkpoints older than the interval are expunged. Contracts kept:

- per-user trash root under the user's home (same layout, so ``-ls`` of
  the trash looks familiar);
- name collisions get a numeric suffix (Trash.java's dodge);
- paths already inside a trash dir are deleted outright (no recursive
  trash-of-trash);
- the API deletes nothing unless asked: ``move_to_trash`` returns False
  when trash is disabled and the CALLER must then really delete.
"""

from __future__ import annotations

import re
import time
from typing import Any

from tpumr.fs.filesystem import FileSystem, Path

CURRENT = "Current"
_CHECKPOINT_RE = re.compile(r"^\d{10,}$")


class Trash:
    def __init__(self, fs: FileSystem, conf: Any,
                 user: "str | None" = None) -> None:
        self.fs = fs
        self.conf = conf
        self.interval_s = float(conf.get("fs.trash.interval", 0)) * 60 \
            if conf is not None else 0.0
        if user is None:
            from tpumr.security import UserGroupInformation
            user = UserGroupInformation.get_current_user(conf).user
        self.user = user

    @property
    def enabled(self) -> bool:
        return self.interval_s > 0

    def trash_root(self, path: "str | Path") -> Path:
        """Per-user trash on the SAME filesystem as ``path``:
        <home>/.Trash (≈ Trash.java's fs.getHomeDirectory()), overridable
        with ``fs.trash.root`` (tests, shared scratch filesystems)."""
        p = Path(path) if not isinstance(path, Path) else path
        base = Path(str(p))
        override = self.conf.get("fs.trash.root") if self.conf else None
        if override:
            base.path = Path(override).path
        else:
            base.path = self.fs.home_directory(self.user) \
                .child(".Trash").path
        return base

    def _in_trash(self, path: Path) -> bool:
        """Inside THIS user's trash root — not any dir merely named
        .Trash (those are ordinary data and deserve trash protection)."""
        root = self.trash_root(path).path.rstrip("/")
        return path.path == root or path.path.startswith(root + "/")

    def move_to_trash(self, path: "str | Path") -> bool:
        """Move into Current; False = caller must delete for real (trash
        disabled, or the path is already trash)."""
        p = Path(path) if not isinstance(path, Path) else path
        if not self.enabled or self._in_trash(p):
            return False
        if not self.fs.exists(p):
            raise FileNotFoundError(str(p))
        root = self.trash_root(p)
        # refuse to trash a dir that CONTAINS the trash (Trash.java's
        # 'Cannot remove ... as it contains the trash'): the rename would
        # nest the tree inside itself
        rp = root.path.rstrip("/")
        pp = p.path.rstrip("/") or "/"
        if rp == pp or rp.startswith(pp + "/") or pp == "/":
            raise OSError(
                f"cannot move {p} to trash: it contains the trash root "
                f"{root} (delete with -skipTrash if you mean it)")
        target = root.child(CURRENT)
        for comp in [c for c in p.path.split("/") if c]:
            target = target.child(comp)
        self.fs.mkdirs(target.parent)
        if self.fs.exists(target):  # collision: numeric suffix
            n = 1
            while self.fs.exists(Path(str(target) + f".{n}")):
                n += 1
            target = Path(str(target) + f".{n}")
        if not self.fs.rename(p, target):
            raise OSError(f"cannot move {p} to trash at {target}")
        return True

    def checkpoint(self) -> "Path | None":
        """Seal Current under a timestamp dir (old deletes start aging)."""
        root = self.trash_root(Path("/"))
        current = root.child(CURRENT)
        if not self.fs.exists(current):
            return None
        ts = int(time.time())
        stamp = root.child(str(ts))
        while self.fs.exists(stamp):  # same-second checkpoint collision
            ts += 1
            stamp = root.child(str(ts))
        if not self.fs.rename(current, stamp):
            raise OSError(f"cannot checkpoint trash: rename {current} "
                          f"-> {stamp} failed")
        return stamp

    def expunge(self) -> int:
        """Delete checkpoints older than the interval; returns how many."""
        root = self.trash_root(Path("/"))
        if not self.fs.exists(root):
            return 0
        removed = 0
        now = time.time()
        for st in self.fs.list_status(root):
            name = st.path.name
            if not _CHECKPOINT_RE.match(name):
                continue
            # checkpoint names ARE wall-clock epochs persisted on disk;
            # ages must be judged against the same clock
            if now - int(name) >= self.interval_s:  # tpulint: disable=clock-arith
                self.fs.delete(st.path, recursive=True)
                removed += 1
        return removed

    def expunge_all(self) -> int:
        """Checkpoint then delete EVERY checkpoint (shell -expunge)."""
        self.checkpoint()
        root = self.trash_root(Path("/"))
        if not self.fs.exists(root):
            return 0
        removed = 0
        for st in self.fs.list_status(root):
            if _CHECKPOINT_RE.match(st.path.name):
                self.fs.delete(st.path, recursive=True)
                removed += 1
        return removed
