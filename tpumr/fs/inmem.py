"""In-memory filesystem for tests and mini-clusters.

≈ the role of the reference's test-time simulated storage (MiniDFSCluster's
simulated data dirs, src/test/org/apache/hadoop/hdfs/MiniDFSCluster.java):
a process-local FS with fake block locations so locality-aware scheduling is
exercisable without disks or daemons.
"""

from __future__ import annotations

import threading
import time
from io import BytesIO
from typing import Any, BinaryIO

from tpumr.fs.filesystem import BlockLocation, FileStatus, FileSystem, Path


class _MemWriter(BytesIO):
    def __init__(self, fs: "InMemoryFileSystem", key: str) -> None:
        super().__init__()
        self._fs = fs
        self._key = key

    def close(self) -> None:
        with self._fs._lock:
            self._fs._files[self._key] = (self.getvalue(), time.time())
        super().close()


class InMemoryFileSystem(FileSystem):
    scheme = "mem"

    #: fake hosts assigned round-robin per block for locality tests
    fake_hosts: list[str] = ["host0", "host1", "host2"]
    block_size = 4 * 1024 * 1024

    def __init__(self, conf: Any = None) -> None:
        self.conf = conf
        self._files: dict[str, tuple[bytes, float]] = {}
        self._dirs: set[str] = {"/"}
        self._lock = threading.RLock()

    @staticmethod
    def _key(path: "str | Path") -> str:
        return Path(path).path

    def open(self, path: "str | Path") -> BinaryIO:
        with self._lock:
            ent = self._files.get(self._key(path))
        if ent is None:
            raise FileNotFoundError(str(path))
        return BytesIO(ent[0])

    def create(self, path: "str | Path", overwrite: bool = True) -> BinaryIO:
        k = self._key(path)
        with self._lock:
            if not overwrite and k in self._files:
                raise FileExistsError(k)
            # implicit parent dirs
            parts = k.split("/")
            for i in range(1, len(parts)):
                self._dirs.add("/".join(parts[:i]) or "/")
        return _MemWriter(self, k)

    def append(self, path: "str | Path") -> BinaryIO:
        k = self._key(path)
        w = _MemWriter(self, k)
        with self._lock:
            if k in self._files:
                w.write(self._files[k][0])
        return w

    def exists(self, path: "str | Path") -> bool:
        k = self._key(path)
        with self._lock:
            return k in self._files or k in self._dirs

    def get_status(self, path: "str | Path") -> FileStatus:
        k = self._key(path)
        with self._lock:
            if k in self._files:
                data, mtime = self._files[k]
                return FileStatus(Path(f"mem://{k}"), length=len(data),
                                  is_dir=False, mtime=mtime,
                                  block_size=self.block_size)
            if k in self._dirs:
                return FileStatus(Path(f"mem://{k}"), is_dir=True)
        raise FileNotFoundError(str(path))

    def list_status(self, path: "str | Path") -> list[FileStatus]:
        k = self._key(path).rstrip("/") or "/"
        prefix = k if k.endswith("/") else k + "/"
        if k == "/":
            prefix = "/"
        seen: dict[str, FileStatus] = {}
        with self._lock:
            names = list(self._files) + list(self._dirs)
        for name in names:
            if name == k or not name.startswith(prefix):
                continue
            rest = name[len(prefix):]
            child = rest.split("/", 1)[0]
            cpath = prefix + child
            if cpath not in seen:
                seen[cpath] = self.get_status(cpath)
        return sorted(seen.values(), key=lambda s: str(s.path))

    def mkdirs(self, path: "str | Path") -> bool:
        k = self._key(path)
        with self._lock:
            parts = k.split("/")
            for i in range(1, len(parts) + 1):
                self._dirs.add("/".join(parts[:i]) or "/")
        return True

    def delete(self, path: "str | Path", recursive: bool = False) -> bool:
        k = self._key(path)
        with self._lock:
            if k in self._files:
                del self._files[k]
                return True
            if k in self._dirs:
                children = [f for f in self._files if f.startswith(k + "/")]
                subdirs = [d for d in self._dirs if d.startswith(k + "/")]
                if (children or subdirs) and not recursive:
                    raise OSError(f"directory not empty: {k}")
                for f in children:
                    del self._files[f]
                for d in subdirs:
                    self._dirs.discard(d)
                self._dirs.discard(k)
                return True
        return False

    def rename(self, src: "str | Path", dst: "str | Path") -> bool:
        s, d = self._key(src), self._key(dst)
        with self._lock:
            if s in self._files:
                self._files[d] = self._files.pop(s)
                parts = d.split("/")
                for i in range(1, len(parts)):
                    self._dirs.add("/".join(parts[:i]) or "/")
                return True
            if s in self._dirs:
                moves = [(f, d + f[len(s):]) for f in list(self._files)
                         if f.startswith(s + "/")]
                for old, new in moves:
                    self._files[new] = self._files.pop(old)
                dmoves = [(x, d + x[len(s):]) for x in list(self._dirs)
                          if x.startswith(s + "/")]
                for old, new in dmoves:
                    self._dirs.discard(old)
                    self._dirs.add(new)
                self._dirs.discard(s)
                self._dirs.add(d)
                parts = d.split("/")
                for i in range(1, len(parts)):
                    self._dirs.add("/".join(parts[:i]) or "/")
                return True
        return False

    def get_block_locations(self, path: "str | Path", offset: int,
                            length: int) -> list[BlockLocation]:
        """Fake block→host placement: block i of a file lives on
        fake_hosts[(crc32(path)+i) % len] — deterministic across processes,
        exercisable by locality tests (≈ MiniDFSCluster rack/host ctor args)."""
        import zlib
        key = self._key(path)
        base = zlib.crc32(key.encode())
        with self._lock:
            ent = self._files.get(key)
        file_len = len(ent[0]) if ent is not None else offset + length
        end = min(offset + length, file_len)
        out = []
        bs = self.block_size
        pos = (offset // bs) * bs
        while pos < end:
            idx = pos // bs
            host = self.fake_hosts[(base + idx) % len(self.fake_hosts)]
            out.append(BlockLocation([host], pos, min(bs, end - pos)))
            pos += bs
        return out or [BlockLocation([self.fake_hosts[base % len(self.fake_hosts)]], offset, 0)]


FileSystem.register("mem", InMemoryFileSystem)
