"""FileSystem SPI.

≈ ``org.apache.hadoop.fs.FileSystem`` (reference: src/core/org/apache/hadoop/
fs/FileSystem.java, 1701 LoC): a scheme-dispatched abstract filesystem with
create/open/rename/delete/listStatus/globStatus, file status metadata, and
block-location hints that feed locality-aware task placement
(FileInputFormat.getSplits → JobInProgress locality caches). Implementations
in-tree: local (``file:``), in-memory (``mem:``, ≈ the test RAM FS) and the
DFS-lite replicated block store (``tdfs:``, tpumr.fs.dfs).
"""

from __future__ import annotations

import fnmatch
import posixpath
import re
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Any, BinaryIO, Callable


class Path:
    """Scheme-qualified path: ``scheme://authority/path`` or bare ``/path``.

    ≈ org.apache.hadoop.fs.Path — purely syntactic; normalization collapses
    '.' and '..' and duplicate slashes.
    """

    __slots__ = ("scheme", "authority", "path")

    def __init__(self, s: "str | Path", child: str | None = None) -> None:
        if isinstance(s, Path):
            self.scheme, self.authority, self.path = s.scheme, s.authority, s.path
        else:
            m = re.match(r"^([A-Za-z][A-Za-z0-9+.-]*)://([^/]*)(/.*|$)", s)
            if m:
                self.scheme = m.group(1)
                self.authority = m.group(2)
                self.path = posixpath.normpath(m.group(3) or "/")
            else:
                self.scheme = ""
                self.authority = ""
                self.path = posixpath.normpath(s) if s else "/"
        if child is not None:
            self.path = posixpath.normpath(posixpath.join(self.path, child))

    def __str__(self) -> str:
        if self.scheme:
            return f"{self.scheme}://{self.authority}{self.path}"
        return self.path

    def __repr__(self) -> str:  # pragma: no cover
        return f"Path({str(self)!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Path) and str(self) == str(other)

    def __hash__(self) -> int:
        return hash(str(self))

    def __lt__(self, other: "Path") -> bool:
        return str(self) < str(other)

    @property
    def name(self) -> str:
        return posixpath.basename(self.path)

    @property
    def parent(self) -> "Path":
        p = Path(self)
        p.path = posixpath.dirname(self.path) or "/"
        return p

    def child(self, name: str) -> "Path":
        return Path(str(self), name)


@dataclass
class FileStatus:
    """≈ org.apache.hadoop.fs.FileStatus."""
    path: Path
    length: int = 0
    is_dir: bool = False
    replication: int = 1
    block_size: int = 64 * 1024 * 1024
    mtime: float = field(default_factory=time.time)
    owner: str = ""


@dataclass
class BlockLocation:
    """≈ org.apache.hadoop.fs.BlockLocation — locality hints for splits."""
    hosts: list[str]
    offset: int
    length: int


class FileSystem(ABC):
    """Abstract filesystem; subclasses register a URI scheme."""

    scheme: str = ""
    _registry: dict[str, "Callable[[Any], FileSystem]"] = {}
    _cache: dict[str, "FileSystem"] = {}
    #: schemes registered on first use (module imported lazily to avoid
    #: pulling daemon deps into every fs consumer)
    _lazy_schemes: dict[str, str] = {"tdfs": "tpumr.dfs.dfs_filesystem",
                                     "tharch": "tpumr.tools.archive",
                                     "gs": "tpumr.fs.objectstore",
                                     "s3": "tpumr.fs.objectstore"}

    # ------------------------------------------------------------ dispatch

    @classmethod
    def register(cls, scheme: str, factory: "Callable[[Any], FileSystem]") -> None:
        cls._registry[scheme] = factory

    @classmethod
    def get(cls, uri: "str | Path", conf: Any = None) -> "FileSystem":
        p = Path(uri) if not isinstance(uri, Path) else uri
        scheme = p.scheme or ((conf.get("fs.default.name") or "file")
                              if conf is not None else "file")
        scheme = Path(scheme).scheme or scheme  # allow full default URIs
        factory = cls._registry.get(scheme)
        if factory is None and scheme in cls._lazy_schemes:
            import importlib
            importlib.import_module(cls._lazy_schemes[scheme])
            factory = cls._registry.get(scheme)
        if factory is None:
            raise ValueError(f"no FileSystem for scheme {scheme!r}; "
                             f"registered: {sorted(cls._registry)}")
        # instances cache per scheme://authority; a factory whose backing
        # store depends on conf (object-store emulation dir) contributes a
        # conf-derived salt so different configs never share an instance
        salt_fn = getattr(factory, "cache_salt", None)
        key = f"{scheme}://{p.authority}" + \
            (f"#{salt_fn(conf)}" if salt_fn else "")
        fs = cls._cache.get(key)
        if fs is None:
            import inspect
            params = inspect.signature(factory).parameters
            if "authority" in params:
                # network filesystems need the URI authority (host:port)
                fs = factory(conf, authority=p.authority)
            else:
                fs = factory(conf)
            cls._cache[key] = fs
        return fs

    @classmethod
    def clear_cache(cls) -> None:
        cls._cache.clear()

    # ------------------------------------------------------------ contract

    @abstractmethod
    def open(self, path: "str | Path") -> BinaryIO: ...

    @abstractmethod
    def create(self, path: "str | Path", overwrite: bool = True) -> BinaryIO: ...

    @abstractmethod
    def append(self, path: "str | Path") -> BinaryIO: ...

    @abstractmethod
    def exists(self, path: "str | Path") -> bool: ...

    @abstractmethod
    def get_status(self, path: "str | Path") -> FileStatus: ...

    @abstractmethod
    def list_status(self, path: "str | Path") -> list[FileStatus]: ...

    @abstractmethod
    def mkdirs(self, path: "str | Path") -> bool: ...

    @abstractmethod
    def delete(self, path: "str | Path", recursive: bool = False) -> bool: ...

    @abstractmethod
    def rename(self, src: "str | Path", dst: "str | Path") -> bool: ...

    # ------------------------------------------------------------ defaults

    def get_block_locations(self, path: "str | Path", offset: int,
                            length: int) -> list[BlockLocation]:
        """Default: single localhost block (local FSes have no placement)."""
        return [BlockLocation(["localhost"], offset, length)]

    def home_directory(self, user: "str | None" = None) -> Path:
        """≈ FileSystem.getHomeDirectory: /user/<name> in the fs's own
        namespace (DFS semantics; LocalFileSystem overrides with $HOME)."""
        if user is None:
            from tpumr.security import UserGroupInformation
            user = UserGroupInformation.get_current_user().user
        return Path(f"/user/{user}")

    def glob_status(self, pattern: "str | Path") -> list[FileStatus]:
        """Glob on the final path component(s) (≈ FileSystem.globStatus —
        supports * ? [] on each component)."""
        pat = Path(pattern)
        comps = [c for c in pat.path.split("/") if c]
        base = Path(str(pat))
        base.path = "/"
        candidates = [base]
        for comp in comps:
            nxt: list[Path] = []
            if re.search(r"[*?\[]", comp):
                for c in candidates:
                    if not self.exists(c) or not self.get_status(c).is_dir:
                        continue
                    for st in self.list_status(c):
                        if fnmatch.fnmatchcase(st.path.name, comp):
                            nxt.append(st.path)
            else:
                for c in candidates:
                    nxt.append(c.child(comp))
            candidates = nxt
        return sorted((self.get_status(c) for c in candidates if self.exists(c)),
                      key=lambda s: str(s.path))

    # convenience

    def read_bytes(self, path: "str | Path") -> bytes:
        with self.open(path) as f:
            return f.read()

    def write_bytes(self, path: "str | Path", data: bytes) -> None:
        with self.create(path) as f:
            f.write(data)

    def list_files(self, path: "str | Path", recursive: bool = False) -> list[FileStatus]:
        out: list[FileStatus] = []
        for st in self.list_status(path):
            if st.is_dir:
                if recursive:
                    out.extend(self.list_files(st.path, True))
            else:
                out.append(st)
        return out

    def copy(self, src: "str | Path", dst_fs: "FileSystem",
             dst: "str | Path", chunk_size: int = 1 << 20) -> int:
        """Chunked stream copy (never materializes the whole file);
        returns bytes copied."""
        total = 0
        with self.open(src) as fin, dst_fs.create(dst) as fout:
            while True:
                chunk = fin.read(chunk_size)
                if not chunk:
                    return total
                fout.write(chunk)
                total += len(chunk)

    def content_length(self, path: "str | Path") -> int:
        """Total bytes under path (file or directory tree)."""
        st = self.get_status(path)
        if not st.is_dir:
            return st.length
        return sum(f.length for f in self.list_files(path, recursive=True))


def get_filesystem(uri: "str | Path", conf: Any = None) -> FileSystem:
    return FileSystem.get(uri, conf)
