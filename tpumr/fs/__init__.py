from tpumr.fs.filesystem import (
    FileSystem, FileStatus, BlockLocation, Path, get_filesystem,
)
from tpumr.fs.local import LocalFileSystem
from tpumr.fs.inmem import InMemoryFileSystem

__all__ = [
    "FileSystem", "FileStatus", "BlockLocation", "Path", "get_filesystem",
    "LocalFileSystem", "InMemoryFileSystem",
]
