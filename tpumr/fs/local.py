"""Local filesystem ≈ ``org.apache.hadoop.fs.RawLocalFileSystem``
(reference: src/core/org/apache/hadoop/fs/RawLocalFileSystem.java). Checksum
wrapping (ChecksumFileSystem) is intentionally not replicated — modern local
storage and the DFS-lite layer carry their own integrity checks.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, BinaryIO

from tpumr.fs.filesystem import FileStatus, FileSystem, Path


class LocalFileSystem(FileSystem):
    scheme = "file"

    def __init__(self, conf: Any = None) -> None:
        self.conf = conf

    def home_directory(self, user: "str | None" = None):
        """$HOME, like RawLocalFileSystem.getHomeDirectory — NOT /user/x
        (which would aim trash at the real filesystem root)."""
        import os

        from tpumr.fs.filesystem import Path
        return Path(os.path.expanduser("~"))

    @staticmethod
    def _local(path: "str | Path") -> str:
        return Path(path).path

    def open(self, path: "str | Path") -> BinaryIO:
        return open(self._local(path), "rb")

    def create(self, path: "str | Path", overwrite: bool = True) -> BinaryIO:
        p = self._local(path)
        if not overwrite and os.path.exists(p):
            raise FileExistsError(p)
        os.makedirs(os.path.dirname(p) or "/", exist_ok=True)
        return open(p, "wb")

    def append(self, path: "str | Path") -> BinaryIO:
        return open(self._local(path), "ab")

    def exists(self, path: "str | Path") -> bool:
        return os.path.exists(self._local(path))

    def get_status(self, path: "str | Path") -> FileStatus:
        p = self._local(path)
        st = os.stat(p)
        return FileStatus(path=Path(f"file://{p}"), length=st.st_size,
                          is_dir=os.path.isdir(p), mtime=st.st_mtime)

    def list_status(self, path: "str | Path") -> list[FileStatus]:
        p = self._local(path)
        return [self.get_status(Path(f"file://{p}").child(name))
                for name in sorted(os.listdir(p))]

    def mkdirs(self, path: "str | Path") -> bool:
        os.makedirs(self._local(path), exist_ok=True)
        return True

    def delete(self, path: "str | Path", recursive: bool = False) -> bool:
        p = self._local(path)
        if not os.path.exists(p):
            return False
        if os.path.isdir(p):
            if recursive:
                shutil.rmtree(p)
            else:
                os.rmdir(p)
        else:
            os.remove(p)
        return True

    def rename(self, src: "str | Path", dst: "str | Path") -> bool:
        s, d = self._local(src), self._local(dst)
        if not os.path.exists(s):
            return False
        os.makedirs(os.path.dirname(d) or "/", exist_ok=True)
        os.replace(s, d)
        return True


FileSystem.register("file", LocalFileSystem)
