"""Object-store FileSystem — GCS-style flat key/blob semantics.

≈ the reference's S3 tier (src/core/org/apache/hadoop/fs/s3/ +
fs/s3native/NativeS3FileSystem.java): expose an eventually-listable flat
object namespace through the FileSystem SPI, modeling object-store
semantics HONESTLY rather than pretending to be POSIX:

- there are no real directories: a "directory" is a key prefix, made
  listable-when-empty by a zero-byte marker object ``<path>/`` (the
  ``_$folder$`` trick of NativeS3FileSystem);
- rename is copy-then-delete per object, NON-atomic across objects —
  job output should land via the OutputCommitter pattern (write to a
  temp prefix, promote), never via concurrent renames;
- objects are immutable blobs: ``create`` buffers locally and uploads on
  close; ``append`` is unsupported;
- reads fetch the object once and serve a seekable view (object stores
  bill per request, not per byte-seek).

The store itself is a pluggable backend (put/get/delete/list): this
environment has zero egress, so the shipped backend is a faithful
local-disk emulation (``fs.gs.emulation.dir`` — one file per object key,
flat, with no directory semantics of its own). A production GCS/S3
client implements the same five calls against the real service; every
path/marker/rename rule above lives in the FS layer and is shared.

GCS is the TPU-idiomatic choice, so the scheme is ``gs://`` (``s3://``
registers as an alias to the same adapter).
"""

from __future__ import annotations

import io
import os
from typing import Any, BinaryIO, Iterator

from tpumr.fs.filesystem import FileStatus, FileSystem, Path


class ObjectBackend:
    """Minimal blob-store contract a real GCS/S3 client would implement."""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def head(self, key: str) -> "tuple[int, float] | None":
        """(size, mtime) of one object, None if absent — a HEAD request,
        never a list."""
        raise NotImplementedError

    def list(self, prefix: str) -> Iterator[tuple[str, int, float]]:
        """Yield (key, size, mtime) for every object under prefix."""
        raise NotImplementedError


class LocalEmulationBackend(ObjectBackend):
    """Flat on-disk object store: one file per key under a root dir, key
    escaped so '/' never creates real directories (the emulation must not
    accidentally inherit POSIX dir semantics)."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    @staticmethod
    def _enc(key: str) -> str:
        return key.replace("%", "%25").replace("/", "%2F")

    @staticmethod
    def _dec(name: str) -> str:
        return name.replace("%2F", "/").replace("%25", "%")

    def _fp(self, key: str) -> str:
        return os.path.join(self.root, self._enc(key))

    def put(self, key: str, data: bytes) -> None:
        if not key:
            raise ValueError("empty object key")
        tmp = self._fp(key) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self._fp(key))

    def get(self, key: str) -> bytes:
        try:
            with open(self._fp(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise FileNotFoundError(f"no such object: {key}") from None

    def delete(self, key: str) -> bool:
        try:
            os.remove(self._fp(key))
            return True
        except FileNotFoundError:
            return False

    def exists(self, key: str) -> bool:
        return bool(key) and os.path.exists(self._fp(key))

    def head(self, key: str) -> "tuple[int, float] | None":
        if not key:
            return None
        try:
            st = os.stat(self._fp(key))
            return st.st_size, st.st_mtime
        except FileNotFoundError:
            return None

    def list(self, prefix: str) -> Iterator[tuple[str, int, float]]:
        for name in sorted(os.listdir(self.root)):
            if name.endswith(".tmp"):
                continue
            key = self._dec(name)
            if key.startswith(prefix):
                st = os.stat(os.path.join(self.root, name))
                yield key, st.st_size, st.st_mtime


class _UploadOnClose(io.BytesIO):
    def __init__(self, backend: ObjectBackend, key: str) -> None:
        super().__init__()
        self._backend = backend
        self._key = key

    def close(self) -> None:
        if not self.closed:
            self._backend.put(self._key, self.getvalue())
        super().close()


class ObjectStoreFileSystem(FileSystem):
    scheme = "gs"

    def __init__(self, conf: Any = None, authority: str = "",
                 scheme: str = "gs") -> None:
        self.conf = conf
        self.bucket = authority
        #: the scheme THIS instance was mounted under (gs or the s3
        #: alias) — returned paths must round-trip through the registry
        self.mount_scheme = scheme
        backend_dir = conf.get("fs.gs.emulation.dir") if conf else None
        if backend_dir:
            # the in-tree default (this environment has zero egress)
            self.backend: ObjectBackend = LocalEmulationBackend(
                os.path.join(backend_dir, authority or "_default"))
            return
        # no emulation dir: the REAL service client (GCS JSON API over
        # stdlib urllib — ≈ S3FileSystem.java:50 talking live S3) when a
        # credential source or explicit endpoint exists
        from tpumr.fs.gcs import GcsJsonBackend, TokenProvider
        tokens = TokenProvider(conf)
        endpoint = conf.get("fs.gs.endpoint") if conf else None
        if endpoint or tokens.token():
            self.backend = GcsJsonBackend(authority, conf,
                                          tokens=tokens)
            return
        raise ValueError(
            "gs:// needs a backend: set fs.gs.emulation.dir for the "
            "local emulation, or provide real-GCS credentials "
            "(fs.gs.auth.token / GCS_OAUTH_TOKEN / run on a GCE or "
            "Cloud-TPU VM with a metadata service account; "
            "fs.gs.endpoint points at an emulator)")

    # ------------------------------------------------------------ keys

    @staticmethod
    def _key(path: "str | Path") -> str:
        p = Path(path) if not isinstance(path, Path) else path
        return p.path.lstrip("/")

    def _qualify(self, key: str) -> Path:
        return Path(f"{self.mount_scheme}://{self.bucket}/{key}")

    # ------------------------------------------------------------ contract

    def open(self, path: "str | Path") -> BinaryIO:
        return io.BytesIO(self.backend.get(self._key(path)))

    def create(self, path: "str | Path",
               overwrite: bool = True) -> BinaryIO:
        key = self._key(path)
        if not overwrite and self.backend.exists(key):
            raise FileExistsError(str(path))
        return _UploadOnClose(self.backend, key)

    def append(self, path: "str | Path") -> BinaryIO:
        raise OSError("object stores do not support append (objects are "
                      "immutable); write a new object instead")

    def exists(self, path: "str | Path") -> bool:
        key = self._key(path)
        if key == "":
            return True  # bucket root
        if self.backend.exists(key) or self.backend.exists(key + "/"):
            return True
        # implicit directory: any object under the prefix
        return next(iter(self.backend.list(key + "/")), None) is not None

    def get_status(self, path: "str | Path") -> FileStatus:
        key = self._key(path)
        if key != "":
            ent = self.backend.head(key)
            if ent is not None:
                return FileStatus(self._qualify(key), is_dir=False,
                                  length=ent[0], mtime=ent[1])
        if self.exists(path):
            return FileStatus(self._qualify(key) if key
                              else Path(f"{self.mount_scheme}://{self.bucket}/"),
                              is_dir=True, length=0)
        raise FileNotFoundError(str(path))

    def list_status(self, path: "str | Path") -> list[FileStatus]:
        key = self._key(path)
        if key != "" and self.backend.exists(key):
            return [self.get_status(path)]
        prefix = key + "/" if key else ""
        seen: dict[str, FileStatus] = {}
        for okey, size, mtime in self.backend.list(prefix):
            rest = okey[len(prefix):]
            if not rest:
                continue  # the dir marker itself
            head, sep, _ = rest.partition("/")
            child = prefix + head
            if sep:  # deeper object -> immediate child is a directory
                seen.setdefault(child, FileStatus(
                    self._qualify(child), is_dir=True, length=0))
            else:
                seen[child] = FileStatus(self._qualify(child),
                                         is_dir=False, length=size,
                                         mtime=mtime)
        if not seen and not self.exists(path):
            raise FileNotFoundError(str(path))
        return [seen[k] for k in sorted(seen)]

    def mkdirs(self, path: "str | Path") -> bool:
        key = self._key(path)
        if key and not self.exists(path):
            self.backend.put(key + "/", b"")  # dir marker object
        return True

    def delete(self, path: "str | Path", recursive: bool = False) -> bool:
        key = self._key(path)
        if key != "" and self.backend.exists(key):
            return self.backend.delete(key)
        prefix = key + "/" if key else ""
        victims = [k for k, _, _ in self.backend.list(prefix)]
        if not victims:
            return False
        if not recursive and any(k != prefix for k in victims):
            raise OSError(f"{path} is a non-empty directory")
        for k in victims:
            self.backend.delete(k)
        return True

    def rename(self, src: "str | Path", dst: "str | Path") -> bool:
        """Copy-then-delete per object — NON-atomic across objects (the
        object-store reality NativeS3FileSystem documents too)."""
        skey, dkey = self._key(src), self._key(dst)
        if self.backend.exists(skey):
            if dkey == "":
                # rename into the bucket root keeps the basename
                dkey = skey.rsplit("/", 1)[-1]
            elif self.exists(dst) and not self.backend.exists(dkey):
                dkey = dkey.rstrip("/") + "/" + skey.rsplit("/", 1)[-1]
            self.backend.put(dkey, self.backend.get(skey))
            self.backend.delete(skey)
            return True
        prefix = skey + "/"
        moved = False
        for okey, _, _ in list(self.backend.list(prefix)):
            self.backend.put(dkey + "/" + okey[len(prefix):],
                             self.backend.get(okey))
            self.backend.delete(okey)
            moved = True
        return moved


def _token_digest(tok: str) -> str:
    if not tok:
        return ""
    import hashlib
    return hashlib.sha256(tok.encode()).hexdigest()[:12]


def _make_factory(scheme: str):
    def factory(conf: Any, authority: str = "") -> ObjectStoreFileSystem:
        return ObjectStoreFileSystem(conf, authority=authority,
                                     scheme=scheme)

    # the instance is bound to its backing store AND its credential: two
    # confs with different emulation dirs, endpoints, or auth tokens must
    # NOT share a cache slot (FileSystem caches per scheme://authority by
    # default; a shared slot would let job B's reads ride job A's bearer
    # token). The token enters the salt as a digest so cache keys never
    # carry the credential itself.
    def _salt(conf):
        # the ENV token is part of the credential identity too: without
        # it, a cached instance pins whatever GCS_OAUTH_TOKEN held at
        # first construction — expired tokens a fresh export can't fix,
        # or one user's requests riding another's bearer
        env_tok = os.environ.get("GCS_OAUTH_TOKEN", "")
        if conf is None:
            return ("None", "None", "None", _token_digest(env_tok))
        tok = str(conf.get("fs.gs.auth.token") or "")
        return (str(conf.get("fs.gs.emulation.dir")),
                str(conf.get("fs.gs.endpoint")),
                _token_digest(tok), _token_digest(env_tok))

    factory.cache_salt = _salt
    return factory


FileSystem.register("gs", _make_factory("gs"))
FileSystem.register("s3", _make_factory("s3"))  # alias, same semantics
