"""Real GCS client for the object-store FileSystem — stdlib only.

≈ the reference's production S3 tier (src/core/org/apache/hadoop/fs/s3/
``S3FileSystem.java:50`` + ``fs/s3native/NativeS3FileSystem.java``, whose
jets3t client talks the live service): this is the live-service
counterpart to :class:`tpumr.fs.objectstore.LocalEmulationBackend`,
implementing the same five-call :class:`ObjectBackend` contract against
the GCS JSON API over ``urllib`` — no third-party SDK, so it works on
any image.

Auth (first match wins):

1. ``fs.gs.auth.token`` in the conf / ``GCS_OAUTH_TOKEN`` in the env —
   an explicit OAuth2 bearer token (what ``gcloud auth
   print-access-token`` emits);
2. the GCE/TPU-VM metadata server (instance service account) — the
   idiomatic path on Cloud TPU nodes, where every VM carries a scoped
   token endpoint. Cached until ~1 min before expiry.

Endpoint override: ``fs.gs.endpoint`` points the client at an emulator
(fake-gcs-server et al.) or a private mirror; the in-tree tests run the
full HTTP client against a loopback emulator this way, so the wire path
is exercised without credentials or egress.

Selection is wired in :mod:`tpumr.fs.objectstore`: emulation when
``fs.gs.emulation.dir`` is set (the in-tree default for this zero-egress
environment), else this client when a token source exists.
"""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Iterator

from tpumr.fs.objectstore import ObjectBackend

_METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/"
                       "v1/instance/service-accounts/default/token")

#: process-wide negative cache for the metadata server: off-GCE hosts
#: (where the DNS lookup may stall for the RESOLVER's timeout, unbounded
#: by urlopen's) must pay that stall at most once per TTL, not on every
#: gs:// filesystem construction
_metadata_down_until = 0.0
_METADATA_RETRY_S = 300.0


class TokenProvider:
    """Bearer-token source with caching for the metadata-server path."""

    def __init__(self, conf: Any = None) -> None:
        self._static = None
        if conf is not None and conf.get("fs.gs.auth.token"):
            self._static = str(conf.get("fs.gs.auth.token"))
        elif os.environ.get("GCS_OAUTH_TOKEN"):
            self._static = os.environ["GCS_OAUTH_TOKEN"]
        self._cached: "tuple[str, float] | None" = None

    def token(self) -> "str | None":
        global _metadata_down_until
        if self._static:
            return self._static
        if self._cached and time.monotonic() < self._cached[1]:
            return self._cached[0]
        if time.monotonic() < _metadata_down_until:
            return None
        req = urllib.request.Request(
            _METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"})
        try:
            with urllib.request.urlopen(req, timeout=2) as resp:
                body = json.loads(resp.read())
        except (OSError, ValueError):
            _metadata_down_until = time.monotonic() + _METADATA_RETRY_S
            return None
        tok = body.get("access_token")
        if not tok:
            _metadata_down_until = time.monotonic() + _METADATA_RETRY_S
            return None
        # refresh a minute early so a token never expires mid-request
        self._cached = (tok, time.monotonic() + float(
            body.get("expires_in", 300)) - 60)
        return tok


def _rfc3339_to_epoch(s: str) -> float:
    from datetime import datetime
    try:
        return datetime.fromisoformat(s.replace("Z", "+00:00")).timestamp()
    except ValueError:
        return 0.0


class GcsJsonBackend(ObjectBackend):
    """GCS JSON API (storage/v1) blob store for one bucket."""

    def __init__(self, bucket: str, conf: Any = None,
                 endpoint: "str | None" = None,
                 tokens: "TokenProvider | None" = None) -> None:
        if not bucket:
            raise ValueError("gs:// needs a bucket authority "
                             "(gs://bucket/path) for the real backend")
        self.bucket = bucket
        self.endpoint = (endpoint
                         or (conf.get("fs.gs.endpoint") if conf else None)
                         or "https://storage.googleapis.com").rstrip("/")
        self.tokens = tokens if tokens is not None else TokenProvider(conf)

    # ------------------------------------------------------------ http

    def _request(self, method: str, url: str, data: bytes = None,
                 content_type: str = "application/octet-stream"):
        headers = {}
        tok = self.tokens.token()
        if tok:
            headers["Authorization"] = f"Bearer {tok}"
        if data is not None:
            headers["Content-Type"] = content_type
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers)
        return urllib.request.urlopen(req, timeout=60)

    def _obj_url(self, key: str, **params: str) -> str:
        q = urllib.parse.urlencode(params)
        return (f"{self.endpoint}/storage/v1/b/"
                f"{urllib.parse.quote(self.bucket, safe='')}/o/"
                f"{urllib.parse.quote(key, safe='')}" + (f"?{q}" if q else ""))

    # ------------------------------------------------------------ contract

    def put(self, key: str, data: bytes) -> None:
        if not key:
            raise ValueError("empty object key")
        url = (f"{self.endpoint}/upload/storage/v1/b/"
               f"{urllib.parse.quote(self.bucket, safe='')}/o?"
               + urllib.parse.urlencode({"uploadType": "media",
                                         "name": key}))
        with self._request("POST", url, data=data) as resp:
            resp.read()

    def get(self, key: str) -> bytes:
        try:
            with self._request("GET",
                               self._obj_url(key, alt="media")) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise FileNotFoundError(f"no such object: "
                                        f"gs://{self.bucket}/{key}") from None
            raise

    def delete(self, key: str) -> bool:
        try:
            with self._request("DELETE", self._obj_url(key)) as resp:
                resp.read()
            return True
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return False
            raise

    def exists(self, key: str) -> bool:
        return bool(key) and self.head(key) is not None

    def head(self, key: str) -> "tuple[int, float] | None":
        if not key:
            return None
        try:
            with self._request(
                    "GET", self._obj_url(key,
                                         fields="size,updated")) as resp:
                meta = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise
        return (int(meta.get("size", 0)),
                _rfc3339_to_epoch(str(meta.get("updated", ""))))

    def list(self, prefix: str) -> Iterator[tuple[str, int, float]]:
        page = None
        base = (f"{self.endpoint}/storage/v1/b/"
                f"{urllib.parse.quote(self.bucket, safe='')}/o")
        while True:
            params = {"prefix": prefix,
                      "fields": "items(name,size,updated),nextPageToken"}
            if page:
                params["pageToken"] = page
            with self._request(
                    "GET",
                    base + "?" + urllib.parse.urlencode(params)) as resp:
                body = json.loads(resp.read())
            for item in body.get("items", []):
                yield (str(item["name"]), int(item.get("size", 0)),
                       _rfc3339_to_epoch(str(item.get("updated", ""))))
            page = body.get("nextPageToken")
            if not page:
                return
