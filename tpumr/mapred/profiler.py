"""Per-task profiling hooks.

≈ the ``mapred.task.profile*`` machinery (reference: mapred/JobConf.java:
1482-1520 getProfileEnabled/getProfileParams/getProfileTaskRange, output
to TaskLog.LogName.PROFILE): opt-in per job, limited to a task-id range
so a huge job profiles a sample rather than everything. The JVM agent
(hprof) becomes cProfile — the Python-native equivalent — dumped as
readable pstats text next to the attempt's other local files and served
by the tracker's status port.

Conf keys (same names as the reference where they exist):

- ``mapred.task.profile``          master switch (default false)
- ``mapred.task.profile.maps``     map task-id ranges, e.g. "0-2,5"
- ``mapred.task.profile.reduces``  reduce task-id ranges (same syntax)
- ``tpumr.task.profile.sort``      pstats sort key (default "cumulative")
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable

PROFILE_FILE = "profile.out"

#: cProfile's sys.monitoring slot is process-global on 3.12 — one
#: profiled section at a time (see maybe_profile)
_PROFILE_SLOT = threading.Lock()


def profile_dir(conf: Any, attempt_id: str, fallback: str) -> str:
    """Where this attempt's profile belongs: the tracker's retained
    userlogs tree when configured (job scratch dirs are purged when the
    job finishes — a profile there would vanish before anyone reads it),
    else the given fallback dir."""
    base = conf.get("tpumr.task.userlogs.dir")
    return os.path.join(base, attempt_id) if base else fallback


def parse_ranges(spec: str) -> "list[tuple[int, int]]":
    """"0-2,5" → [(0,2),(5,5)] ≈ Configuration.IntegerRanges."""
    out = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        lo, sep, hi = part.partition("-")
        a = int(lo)
        b = int(hi) if sep and hi.strip() else a
        out.append((min(a, b), max(a, b)))
    return out


def in_ranges(n: int, spec: str) -> bool:
    return any(lo <= n <= hi for lo, hi in parse_ranges(spec))


def should_profile(conf: Any, task: Any) -> bool:
    if not conf.get_boolean("mapred.task.profile", False):
        return False
    key = "mapred.task.profile.maps" if task.is_map \
        else "mapred.task.profile.reduces"
    return in_ranges(task.partition, conf.get(key, "0-2"))


def maybe_profile(conf: Any, task: Any, local_dir: str,
                  fn: Callable[[], Any]) -> Any:
    """Run ``fn`` under cProfile when the job asks for this task; the
    pstats report lands in ``<local_dir>/profile.out``. Profiling must
    never fail the task: dump errors are swallowed, and the task's own
    exceptions propagate unchanged."""
    try:
        enabled = should_profile(conf, task)
    except Exception:  # noqa: BLE001 — a typo'd range spec ("0:2") must
        enabled = False  # disable profiling, never fail the task
    if not enabled:
        return fn()
    import cProfile
    # cPython 3.12 cProfile claims a PROCESS-global sys.monitoring tool
    # slot: two attempts profiling concurrently (tracker threads in one
    # process, MiniMRCluster) would die with "Another profiling tool is
    # already active" — serialize profiled sections instead
    prof = cProfile.Profile()
    try:
        # only runcall needs the slot (released when it disables the
        # profiler) — the report dump happens outside the lock
        with _PROFILE_SLOT:
            return prof.runcall(fn)
    finally:
        _dump_profile(prof, conf, task, local_dir)


def profile_top_lines(text: str, n: int = 25) -> "list[str]":
    """The header + first ``n`` data rows of a pstats report — the
    task-detail-page summary (full text stays one click away). Keeps
    everything through the column-header line, then ``n`` rows."""
    lines = text.splitlines()
    header_end = next((i for i, ln in enumerate(lines)
                       if ln.lstrip().startswith("ncalls")), None)
    if header_end is None:
        return lines[:n]
    return lines[:header_end + 1 + n]


def _dump_profile(prof: Any, conf: Any, task: Any, local_dir: str) -> None:
    try:
        import io
        import pstats
        os.makedirs(local_dir, exist_ok=True)
        buf = io.StringIO()
        sort = conf.get("tpumr.task.profile.sort", "cumulative")
        pstats.Stats(prof, stream=buf).sort_stats(sort) \
            .print_stats(60)
        with open(os.path.join(local_dir, PROFILE_FILE), "w") as f:
            f.write(f"# profile of {task.attempt_id}\n")
            f.write(buf.getvalue())
    except Exception:  # noqa: BLE001 — profiling is best-effort
        pass
