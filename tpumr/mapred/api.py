"""User-facing MapReduce API.

≈ the reference's old API (``org.apache.hadoop.mapred.{Mapper,Reducer,
MapRunnable,MapRunner,Partitioner,Reporter,OutputCollector}``). The
class-based contract is kept — configure/map|reduce/close lifecycle,
OutputCollector + Reporter threaded through — because the hybrid scheduler
and the TPU runner select *runners* around it exactly like the reference
selects PipesMapRunner vs PipesGPUMapRunner (mapred/MapTask.java:433-438).

Device-kernel jobs don't subclass Mapper: they name a registered kernel
(JobConf.set_map_kernel) and the TPU map runner (tpumr.mapred.tpu_runner)
consumes whole batches. A Mapper subclass remains the CPU fallback for the
same job, which is what makes hybrid CPU/TPU assignment meaningful.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterator

from tpumr.core.counters import Counters
from tpumr.io.writable import deserialize, serialize


class TaskKilledError(Exception):
    """Raised inside a task when its attempt was killed (preemption,
    speculative-race loss, job kill) — surfaces as state KILLED (requeue,
    no attempt budget), never FAILED."""


class Reporter:
    """≈ org.apache.hadoop.mapred.Reporter: progress + status + counters.
    Also the cooperative-cancellation seam: in-process task threads cannot
    be interrupted, so record loops poll :meth:`aborted` and bail with
    :class:`TaskKilledError` — this is what makes a preemption kill free
    its slot mid-task instead of at natural completion."""

    def __init__(self, counters: Counters | None = None,
                 on_progress: Callable[[float], None] | None = None,
                 abort_check: Callable[[], bool] | None = None) -> None:
        self.counters = counters or Counters()
        self._on_progress = on_progress
        self._abort_check = abort_check
        self.status = ""
        #: liveness ticks for the tracker's hung-task reaper: wait loops
        #: that are legitimately idle-but-alive (a reduce blocked on a
        #: not-yet-rerun map's location, a penalty-boxed fetcher) call
        #: keepalive() so silence stays the hang signal, activity doesn't
        #: have to mean record throughput (≈ Hadoop reduces calling
        #: reporter.progress() every fetch-loop iteration)
        self.ticks = 0

    def set_status(self, status: str) -> None:
        self.status = status
        # a status line IS a progress report (the in-process reaper sees
        # the string itself; an isolated child only ships ticks, so the
        # bump is what carries set_status liveness over the umbilical)
        self.ticks += 1

    def keepalive(self) -> None:
        self.ticks += 1   # GIL-atomic int bump; no lock on the wait path

    def progress(self, fraction: float | None = None) -> None:
        self.ticks += 1
        if self._on_progress is not None and fraction is not None:
            self._on_progress(fraction)

    def aborted(self) -> bool:
        return self._abort_check is not None and self._abort_check()

    def raise_if_aborted(self) -> None:
        if self.aborted():
            raise TaskKilledError("attempt killed while running")

    def incr_counter(self, group: str, name: str, amount: int = 1) -> None:
        self.counters.incr(group, name, amount)


class OutputCollector:
    """≈ org.apache.hadoop.mapred.OutputCollector, plus an optional bulk
    lane: mappers producing fixed-width byte records in arrays (teragen)
    can hand ``[n, klen+vlen]`` rows over in one ``collect_fixed_rows``
    call; sinks without a vectorized path degrade it to per-record
    ``collect`` calls."""

    def __init__(self, fn: Callable[[Any, Any], None],
                 fixed_rows_fn: "Callable[[Any, int], None] | None" = None
                 ) -> None:
        self._fn = fn
        self._fixed_rows_fn = fixed_rows_fn

    def collect(self, key: Any, value: Any) -> None:
        self._fn(key, value)

    def collect_fixed_rows(self, rows: Any, klen: int) -> None:
        if self._fixed_rows_fn is not None:
            self._fixed_rows_fn(rows, klen)
            return
        for i in range(rows.shape[0]):
            self._fn(rows[i, :klen].tobytes(), rows[i, klen:].tobytes())

    __call__ = collect


class JobConfigurable:
    def configure(self, conf: Any) -> None:  # ≈ JobConfigurable.configure
        pass

    def close(self) -> None:  # ≈ Closeable.close
        pass


class Mapper(JobConfigurable):
    """≈ org.apache.hadoop.mapred.Mapper: map(key, value, output, reporter)."""

    def map(self, key: Any, value: Any, output: OutputCollector,
            reporter: Reporter) -> None:
        raise NotImplementedError


class Reducer(JobConfigurable):
    """≈ org.apache.hadoop.mapred.Reducer:
    reduce(key, values_iterator, output, reporter)."""

    def reduce(self, key: Any, values: Iterator[Any], output: OutputCollector,
               reporter: Reporter) -> None:
        raise NotImplementedError


class IdentityMapper(Mapper):
    """≈ mapred/lib/IdentityMapper.java."""

    #: declares the stateless pass-through contract: the framework may
    #: bypass map() and move records in bulk (device-shuffle fast path)
    identity_map = True

    def map(self, key, value, output, reporter):
        output.collect(key, value)


class IdentityReducer(Reducer):
    """≈ mapred/lib/IdentityReducer.java."""

    def reduce(self, key, values, output, reporter):
        for v in values:
            output.collect(key, v)


class Partitioner(JobConfigurable):
    def get_partition(self, key: Any, value: Any, num_partitions: int) -> int:
        raise NotImplementedError


class HashPartitioner(Partitioner):
    """≈ mapred/lib/HashPartitioner.java: (hash & MAX) % n — here a stable
    digest of the serialized key (Python's hash() is process-randomized, and
    partition choice must agree across hosts)."""

    def get_partition(self, key: Any, value: Any, num_partitions: int) -> int:
        import zlib
        return zlib.crc32(serialize(key)) % num_partitions


class KeyFieldBasedPartitioner(Partitioner):
    """≈ mapred/lib/KeyFieldBasedPartitioner.java (simplified): partitions on
    the first ``num_fields`` tab-separated fields of a text key."""

    def __init__(self, num_fields: int = 1, separator: str = "\t") -> None:
        self.num_fields = num_fields
        self.separator = separator

    def get_partition(self, key: Any, value: Any, num_partitions: int) -> int:
        import zlib
        s = key if isinstance(key, str) else str(key)
        prefix = self.separator.join(s.split(self.separator)[: self.num_fields])
        return zlib.crc32(prefix.encode()) % num_partitions


# ------------------------------------------------------------ comparators


class DeserializingComparator:
    """Default sort order: natural Python ordering of the deserialized key
    (≈ WritableComparable.compareTo on typed keys)."""

    def sort_key(self, kbytes: bytes) -> Any:
        return deserialize(kbytes)


class RawComparator:
    """Byte-lexicographic raw order (≈ WritableComparator.compareBytes) —
    correct for keys whose serialized form sorts like the logical key
    (e.g. fixed-width byte keys: terasort)."""

    def sort_key(self, kbytes: bytes) -> Any:
        return kbytes


# ------------------------------------------------------------ map runners


class MapRunnable(JobConfigurable):
    """≈ org.apache.hadoop.mapred.MapRunnable. The reference grew a 4-arg
    GPU overload run(input, output, reporter, runOnGPU)
    (mapred/MapRunnable.java:50-53); here device placement arrives via
    ``task_ctx`` so every runner sees the same signature."""

    def run(self, reader: Any, output: OutputCollector, reporter: Reporter,
            task_ctx: Any = None) -> None:
        raise NotImplementedError


class MapRunner(MapRunnable):
    """Default record-loop runner ≈ mapred/MapRunner.java:71-92."""

    def __init__(self, mapper: Mapper | None = None) -> None:
        self.mapper = mapper
        self.conf = None

    def configure(self, conf: Any) -> None:
        self.conf = conf
        if self.mapper is None:
            from tpumr.utils.reflection import new_instance
            cls = conf.get_mapper_class() or IdentityMapper
            self.mapper = new_instance(cls, conf)

    def run(self, reader, output, reporter, task_ctx=None) -> None:
        assert self.mapper is not None
        try:
            for key, value in reader:
                self.mapper.map(key, value, output, reporter)
        finally:
            self.mapper.close()


class MultithreadedMapRunner(MapRunner):
    """Thread-pooled record runner ≈ mapred/lib/MultithreadedMapRunner.java
    (parallelism strategy #8, SURVEY.md §2.5): N worker threads call
    ``map()`` concurrently within ONE slot — for mappers that block on
    external IO (RPC lookups, fetches), not for CPU parallelism (the GIL;
    CPU-bound batching belongs to the kernel/batch runners).

    Contracts kept from the reference: one shared mapper instance (the
    user's map() must be thread-safe, as documented there); the output
    collector is serialized behind a lock (≈ its synchronized collector
    wrapper); the first worker exception aborts the run and re-raises on
    the main thread (≈ its ioException/runtimeException fields); thread
    count from ``mapred.map.multithreadedrunner.threads`` (same key,
    default 10)."""

    def run(self, reader, output, reporter, task_ctx=None) -> None:
        assert self.mapper is not None
        import queue as _queue

        n_threads = max(1, self.conf.get_int(
            "mapred.map.multithreadedrunner.threads", 10))
        out_lock = threading.Lock()
        locked_collect = OutputCollector(
            lambda k, v: _locked_call(out_lock, output, k, v))
        work: _queue.Queue = _queue.Queue(maxsize=n_threads * 2)
        errors: list[BaseException] = []
        err_lock = threading.Lock()

        def worker() -> None:
            while True:
                item = work.get()
                if item is None:
                    return
                try:
                    self.mapper.map(item[0], item[1], locked_collect,
                                    reporter)
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    with err_lock:
                        errors.append(e)

        threads = [threading.Thread(target=worker,
                                    name=f"mt-map-{i}", daemon=True)
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        try:
            for key, value in reader:
                with err_lock:
                    if errors:
                        break
                work.put((key, value))
        finally:
            for _ in threads:
                work.put(None)
            for t in threads:
                t.join()
            self.mapper.close()
        if errors:
            raise errors[0]


def _locked_call(lock: "threading.Lock", output: Any, k: Any,
                 v: Any) -> None:
    with lock:
        output.collect(k, v)
