"""Task schedulers: the pluggable SPI + the hybrid CPU/TPU scheduler.

≈ ``org.apache.hadoop.mapred.TaskScheduler`` (SPI) and the GPU-modified
``JobQueueTaskScheduler`` (reference: src/mapred/org/apache/hadoop/mapred/
JobQueueTaskScheduler.java, 628 LoC — the Shirahata et al. hybrid
scheduler, SURVEY.md §2.1). The algorithm is ported faithfully:

- per-job mean CPU/TPU map runtimes → ``accelerationFactor = cpuMean/tpuMean``
  (:127-178);
- **optional scheduling** (:78, :290-291): when
  ``mapred.jobtracker.map.optionalscheduling`` is on and the remaining map
  load fits the accelerator capacity
  (``pendingMapLoad < accelFactor × tpuCapacity × numTrackers``), the CPU
  pass is SKIPPED — work converges onto the faster backend;
- the TPU pass requires the job to have a device kernel (≈ the
  ``hadoop.pipes.gpu.executable`` gate :342-347) and assigns a concrete free
  device id per task (:355-361), consuming device availability locally
  within the same heartbeat (:373-378);
- at most ONE reduce task per heartbeat (:527-560);
- the reference's commented-out load-split minimization ``f(x,y) =
  max(⌈x/n_cpu⌉·t_cpu, ⌈y/n_tpu⌉·t_tpu)`` (:181-219) is implemented here as
  a selectable mode (``tpumr.scheduler.mode = minimize``) instead of dead
  code.
"""

from __future__ import annotations

import math
from typing import Any, Protocol

from tpumr.core import confkeys
from tpumr.mapred.job_in_progress import (JobInProgress, JobState,
                                          priority_rank)
from tpumr.mapred.task import Task


class TaskTrackerManager(Protocol):
    """What a scheduler needs from the master (≈ mapred/TaskTrackerManager
    interface — the seam the reference's scheduler unit tests fake)."""

    def running_jobs(self) -> list[JobInProgress]: ...
    def num_trackers(self) -> int: ...
    def total_slots(self) -> dict: ...   # {"cpu": n, "tpu": n, "reduce": n}
    # optional: monotonically bumped when the running-job set (or a job
    # priority) changes — lets the FIFO order cache skip its re-sort.
    # Fakes without it just lose the caching (getattr-guarded).
    # def jobs_version(self) -> int: ...
    # optional: tag -> live tracker names whose piggybacked devcache
    # inventory holds the tag (the affinity pass's cross-tracker view).
    # Fakes without it just lose deferral (getattr-guarded).
    # def devcache_tag_index(self) -> dict[str, set[str]]: ...


class TaskScheduler:
    """SPI ≈ mapred/TaskScheduler.java — pluggable via
    ``mapred.jobtracker.taskScheduler``."""

    def __init__(self) -> None:
        self.manager: TaskTrackerManager | None = None
        self.conf: Any = None
        #: optional MetricsRegistry wired by the master: scheduling is a
        #: per-heartbeat decision on the control plane's critical path,
        #: so its wall time is a first-class distribution
        #: (``assign_seconds``) and its output a per-backend counter set
        self.metrics: Any = None

    def set_manager(self, manager: TaskTrackerManager) -> None:
        self.manager = manager

    def configure(self, conf: Any) -> None:
        self.conf = conf

    def assign_tasks(self, tracker_status: dict) -> list[Task]:
        raise NotImplementedError

    def before_heartbeat(self, tracker_status: dict) -> None:
        """Observation hook run on EVERY heartbeat, before kill-action
        generation and regardless of free slots (assign_tasks only runs
        when the tracker asks for work — a fully saturated cluster never
        does, which is precisely when preemption logic must still fire)."""


def _free_tpu_devices(tracker_status: dict) -> list[int]:
    """Free physical device ids, recomputed from running task statuses each
    heartbeat (≈ TaskTrackerStatus.availableGPUDevices(),
    TaskTrackerStatus.java:536-550 — inferred, not leased)."""
    avail = tracker_status.get("available_tpu_devices")
    if avail is None:
        avail = [True] * int(tracker_status.get("max_tpu_map_slots", 0))
    return [i for i, free in enumerate(avail) if free]


def _priority_fifo(jobs: list[JobInProgress]) -> list[JobInProgress]:
    """The reference's FIFO queue order (JobQueueJobInProgressListener.
    FIFO_JOB_QUEUE_COMPARATOR): priority first, then submit time, then
    job id — so ``job -set-priority`` reorders the queue live.

    Submit time is the job's ``sched_anchor``: normally its own submit
    stamp, but pipeline STAGE jobs inherit their pipeline's submit time
    — a chain's late stages keep the chain's queue position instead of
    re-queueing behind every job submitted while the early stages ran
    (start_time stays the tiebreak so stages still order among
    themselves)."""
    return sorted(jobs, key=lambda j: (priority_rank(j.priority),
                                       getattr(j, "sched_anchor",
                                               j.start_time),
                                       j.start_time, str(j.job_id)))


class HybridQueueScheduler(TaskScheduler):
    """FIFO job queue + Shirahata hybrid CPU/TPU map placement.

    Subclass seams: ``_map_job_order`` / ``_reduce_job_order`` decide which
    job is offered the next free slot — the fair and capacity schedulers
    (tpumr.contrib) override only these, inheriting the hybrid CPU/TPU
    passes (an upgrade over the reference, whose contrib schedulers were
    GPU-blind — SURVEY.md §1 L5)."""

    #: FIFO-order cache state: (manager jobs_version, len(jobs)) → sorted
    #: list. The order hooks run PER FREE SLOT per heartbeat (contract
    #: below), which at fleet scale meant thousands of identical
    #: O(jobs log jobs) sorts per second; priority and submit time only
    #: change when the master bumps its jobs_version, so the sorted
    #: order is reused until it does. Subclass overrides (fair/capacity
    #: recompute shares per slot) are unaffected — the cache lives in
    #: the base implementation only.
    _fifo_key: "tuple | None" = None
    _fifo_cache: "list[JobInProgress]" = []

    def __init__(self) -> None:
        super().__init__()
        # --- devcache-affinity placement state ---
        #: job id → TPU passes its maps were held back waiting for a
        #: tag-warm tracker's heartbeat; reset on a warm hit, pinned at
        #: the budget once spent (the job then places cold anywhere)
        self._affinity_defers: "dict[str, int]" = {}
        #: (enabled, defer budget) — conf is master-fixed; parsed once
        self._affinity_conf: "tuple[bool, int] | None" = None
        # per-heartbeat state (the passes run per free slot)
        self._beat_local_tags: "frozenset[str]" = frozenset()
        self._beat_tag_index: "dict[str, Any] | None" = None
        self._beat_affinity: "dict[str, bool]" = {}

    def _priority_fifo_cached(self,
                              jobs: list[JobInProgress]) -> list[JobInProgress]:
        ver_fn = getattr(self.manager, "jobs_version", None)
        if ver_fn is None:
            return _priority_fifo(jobs)
        key = (ver_fn(), len(jobs))
        if key != self._fifo_key:
            self._fifo_cache = _priority_fifo(jobs)
            self._fifo_key = key
        return self._fifo_cache

    def _map_job_order(self, jobs: list[JobInProgress]) -> list[JobInProgress]:
        return self._priority_fifo_cached(jobs)

    def _reduce_job_order(self,
                          jobs: list[JobInProgress]) -> list[JobInProgress]:
        return self._priority_fifo_cached(jobs)

    def _begin_assignment(self, tts: dict) -> None:
        """Called once per heartbeat before the passes — subclasses cache
        heartbeat-invariant state here (the order hooks run per free slot)."""

    # ------------------------------------------ devcache-affinity placement

    def _begin_affinity(self, tts: dict) -> None:
        """Per-heartbeat affinity context: the asking tracker's
        piggybacked devcache tag inventory, the master's cross-tracker
        tag index (getattr-guarded — fakes without it lose deferral,
        not correctness), and a fresh per-job decision memo so the
        per-slot inner loops charge each job's defer budget at most
        once per heartbeat. Lives in ``_assign_tasks`` rather than
        ``_begin_assignment`` because contrib subclasses override the
        latter without chaining up."""
        if self._affinity_conf is None:
            if self.conf is None:
                self._affinity_conf = (True, 3)
            else:
                self._affinity_conf = (
                    confkeys.get_boolean(self.conf,
                                         "tpumr.scheduler.affinity"),
                    max(0, confkeys.get_int(
                        self.conf,
                        "tpumr.scheduler.affinity.defer.passes")))
        self._beat_affinity = {}
        self._beat_local_tags = frozenset(tts.get("devcache_tags") or ())
        self._beat_tag_index = None
        if self._affinity_conf[0]:
            index_fn = getattr(self.manager, "devcache_tag_index", None)
            if index_fn is not None:
                self._beat_tag_index = index_fn()

    def _affinity_defer(self, job: JobInProgress) -> bool:
        """Should the TPU pass hold this job's maps back from the asking
        tracker this heartbeat? True only when the job names side-input
        tags, this tracker's devcache is cold on all of them, some OTHER
        live tracker is warm, and the job still has defer budget — a
        bounded wait for the warm tracker's next heartbeat, never
        starvation (the budget pins once spent and the job places cold).
        FIFO/priority order is never reordered, only deferred."""
        jid = str(job.job_id)
        memo = self._beat_affinity
        if jid in memo:
            return memo[jid]
        memo[jid] = d = self._affinity_defer_uncached(job, jid)
        return d

    def _affinity_defer_uncached(self, job: JobInProgress,
                                 jid: str) -> bool:
        enabled, budget = self._affinity_conf or (True, 3)
        if not enabled:
            return False
        tags_fn = getattr(job, "devcache_tags", None)
        tags = tags_fn() if tags_fn is not None else ()
        if not tags:
            return False
        reg = self.metrics
        if any(t in self._beat_local_tags for t in tags):
            # warm here: assign here (and forgive any defer history)
            self._affinity_defers.pop(jid, None)
            if reg is not None:
                reg.incr("affinity_warm_hits")
            return False
        index = self._beat_tag_index
        if not index or not any(index.get(t) for t in tags):
            return False   # nobody warm anywhere — no reason to wait
        spent = self._affinity_defers.get(jid, 0)
        if spent >= budget:
            if reg is not None:
                reg.incr("affinity_cold_assigns")
            return False   # budget pinned: place cold rather than starve
        self._affinity_defers[jid] = spent + 1
        if reg is not None:
            reg.incr("affinity_defers")
        return True

    def assign_tasks(self, tts: dict) -> list[Task]:
        reg = self.metrics
        if reg is None:
            return self._assign_tasks(tts)
        with reg.histogram("assign_seconds").time():
            assigned = self._assign_tasks(tts)
        for task in assigned:
            if not task.is_map:
                reg.incr("assigned_reduces")
            elif task.run_on_tpu:
                reg.incr("assigned_tpu_maps")
            else:
                reg.incr("assigned_cpu_maps")
        return assigned

    def _assign_tasks(self, tts: dict) -> list[Task]:
        assert self.manager is not None
        jobs = [j for j in self.manager.running_jobs()
                if j.state == JobState.RUNNING]
        if not jobs:
            return []
        self._begin_assignment(tts)
        self._begin_affinity(tts)
        n_trackers = max(1, self.manager.num_trackers())
        host = tts.get("host", "")

        max_cpu = int(tts.get("max_cpu_map_slots", 0))
        max_tpu = int(tts.get("max_tpu_map_slots", 0))
        max_red = int(tts.get("max_reduce_slots", 0))
        run_cpu = int(tts.get("count_cpu_map_tasks", 0))
        run_tpu = int(tts.get("count_tpu_map_tasks", 0))
        run_red = int(tts.get("count_reduce_tasks", 0))
        free_cpu = max(0, max_cpu - run_cpu)
        free_tpu = max(0, max_tpu - run_tpu)
        free_red = max(0, max_red - run_red)
        free_devices = _free_tpu_devices(tts)
        # memory matching (≈ CapacityTaskScheduler): a tracker reporting
        # finite memory only receives tasks whose declared demand fits;
        # consumed locally as this heartbeat assigns. -1 / absent = off.
        mem_left = int(tts.get("available_memory_mb", -1))

        def fits(demand_mb: int) -> bool:
            return mem_left < 0 or demand_mb <= mem_left

        assigned: list[Task] = []

        cluster_mode = str(self.conf.get("tpumr.scheduler.mode",
                                         "shirahata")) \
            if self.conf else "shirahata"

        # ---- per-JOB CPU budgets (a starved hybrid job must not block CPU
        # slots for kernel-less jobs that can only ever run on CPU).
        # Computed LAZILY on first visit: the passes walk the job order
        # front-to-first-assignable, so a wide queue's tail — the common
        # case at fleet scale, where this ran per asking heartbeat —
        # never pays the accel-profile/minimizer arithmetic.
        cpu_budget: dict[str, int] = {}

        def budget_of(job: JobInProgress) -> int:
            jid = str(job.job_id)
            b = cpu_budget.get(jid)
            if b is not None:
                return b
            b = free_cpu
            if job.has_kernel() and not job.tpu_disabled:
                # (quarantined jobs keep the full budget: the TPU pass
                # skips them entirely, so neither starvation mode may
                # zero their CPU share — that combination would deadlock
                # the job with pending maps no pass can assign)
                accel = job.acceleration_factor()
                # per-job override, same seam as optionalscheduling (a
                # job may opt into the f(x,y) minimizer on a shirahata
                # cluster)
                mode = str(job.conf.get("tpumr.scheduler.mode",
                                        cluster_mode))
                if mode == "minimize":
                    # the f(x,y) optimum may put everything on TPU —
                    # demoted (CPU-pinned) TIPs still need a floor of
                    # CPU slots
                    b = max(
                        self._minimize_cpu_share(job, free_cpu,
                                                 max_tpu * n_trackers),
                        min(free_cpu, job.cpu_pinned_pending_count()))
                elif (self._optional_scheduling(job)
                        and job.cpu_pinned_pending_count() == 0
                        and job.pending_map_count()
                        < accel * max_tpu * n_trackers):
                    # optional scheduling: starve THIS job's CPU share
                    # so its remaining maps converge to the accelerator
                    # (:290-327). CPU-pinned (demoted) TIPs lift the
                    # starvation: they can only ever run on the CPU pass
                    b = 0
            cpu_budget[jid] = b
            return b

        # ---- TPU pass first (reference order fills GPU after CPU; filling
        # the scarcer, faster pool first avoids giving a map to a CPU slot
        # that a free device could have taken in the same heartbeat)
        for _ in range(free_tpu):
            if not free_devices:
                break
            task = None
            for job in self._map_job_order(jobs):
                if not job.tpu_eligible():
                    # ≈ gpu-executable gate (:342-347), plus the job-
                    # level accelerator quarantine
                    continue
                if job.pending_map_count() == 0 \
                        and not (job.speculative
                                 and not job.speculation_hold):
                    # lock-free precheck (len of a set, stale by at most
                    # a beat): obtain re-checks under the job lock, this
                    # just skips the lock round trip for drained jobs
                    # (a brownout speculation hold drains them too)
                    continue
                if not fits(job.map_memory_mb()):
                    continue
                if self._affinity_defer(job):
                    # this tracker's devcache is cold on the job's side
                    # inputs and a warm tracker is live — hold the maps
                    # for its heartbeat (bounded by the defer budget)
                    continue
                device = free_devices[0]
                task = job.obtain_new_map_task(host, run_on_tpu=True,
                                               tpu_device_id=device,
                                               rack=tts.get("rack"))
                if task is not None:
                    free_devices.pop(0)  # consume locally (:373-378)
                    break
            if task is None:
                break
            assigned.append(task)
            if mem_left >= 0:
                mem_left -= task.memory_mb

        # ---- CPU pass (:290-327)
        for _ in range(free_cpu):
            task = None
            for job in self._map_job_order(jobs):
                if job.pending_map_count() == 0 \
                        and not (job.speculative
                                 and not job.speculation_hold):
                    continue   # lock-free precheck, same as TPU pass
                if budget_of(job) <= 0:
                    continue
                if not fits(job.map_memory_mb()):
                    continue
                task = job.obtain_new_map_task(host, run_on_tpu=False,
                                               rack=tts.get("rack"))
                if task is not None:
                    cpu_budget[str(job.job_id)] -= 1
                    break
            if task is None:
                break
            assigned.append(task)
            if mem_left >= 0:
                mem_left -= task.memory_mb

        # ---- reduce pass: at most one per heartbeat (:527-560)
        if free_red > 0:
            for job in self._reduce_job_order(jobs):
                if job.pending_reduce_count() == 0 \
                        and not (job.speculative_reduces
                                 and not job.speculation_hold):
                    # lock-free precheck: most jobs in a wide queue have
                    # their (few) reduces already placed — without this,
                    # every heartbeat's reduce pass took every job's
                    # lock just to hear "nothing pending"
                    continue
                if not fits(job.reduce_memory_mb()):
                    continue
                task = job.obtain_new_reduce_task(host)
                if task is not None:
                    assigned.append(task)
                    break

        return assigned

    def _optional_scheduling(self, job: JobInProgress) -> bool:
        return bool(job.conf.get("mapred.jobtracker.map.optionalscheduling",
                                 False))

    def _minimize_cpu_share(self, job: JobInProgress, n_cpu: int,
                            n_tpu_total: int) -> int:
        """Implemented form of the commented-out minimization
        (JobQueueTaskScheduler.java:181-219): choose the CPU share x of the
        pending maps minimizing
        ``f(x, y) = max(⌈x/n_cpu⌉·t_cpu, ⌈y/n_tpu⌉·t_tpu)``; returns how
        many CPU slots are worth filling this heartbeat (0 when the optimum
        puts everything on TPU)."""
        pending = job.pending_map_count()
        t_cpu = job.cpu_map_mean_time()
        t_tpu = job.tpu_map_mean_time()
        if pending == 0 or t_cpu <= 0 or t_tpu <= 0 or n_tpu_total == 0:
            return n_cpu  # no profile yet: behave like plain FIFO
        best_x, best_f = 0, math.inf
        for x in range(pending + 1):
            y = pending - x
            f = max(math.ceil(x / max(1, n_cpu)) * t_cpu,
                    math.ceil(y / n_tpu_total) * t_tpu)
            if f < best_f:
                best_x, best_f = x, f
        return min(n_cpu, best_x)


class FifoScheduler(HybridQueueScheduler):
    """Plain FIFO: hybrid logic off — every map is a CPU map unless the
    tracker has TPU slots and the job a kernel (no starvation, no
    minimization). Mirrors stock JobQueueTaskScheduler behavior."""

    def _optional_scheduling(self, job: JobInProgress) -> bool:
        return False
