"""JobClient — submission + monitoring.

≈ ``org.apache.hadoop.mapred.JobClient`` (reference: src/mapred/org/apache/
hadoop/mapred/JobClient.java, 2093 LoC): split computation happens at the
CLIENT (writeSplits, :897,973-981), output specs are checked before
submission, then the job goes to the master over the submission protocol and
``RunningJob`` polls status. With no ``mapred.job.tracker`` configured the
job runs through LocalJobRunner (the reference's "local" default).
"""

from __future__ import annotations

import time
from typing import Any

from tpumr.core.counters import Counters
from tpumr.ipc.rpc import RpcClient
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.local_runner import JobResult, LocalJobRunner
from tpumr.utils.reflection import new_instance


class RunningJob:
    """≈ org.apache.hadoop.mapred.RunningJob.

    Master-restart aware: a restarted master recovers interrupted jobs
    under NEW ids and serves the old id through its ``job_recovered``
    alias — every status poll re-reads the authoritative id from the
    response and rebinds, so a polling client follows the resubmitted
    job instead of reporting it vanished."""

    def __init__(self, client: RpcClient, job_id: str) -> None:
        self._client = client
        self.job_id = job_id

    def status(self) -> dict:
        st = self._client.call("get_job_status", self.job_id)
        new_id = st.get("job_id")
        if new_id and new_id != self.job_id:
            self.job_id = new_id
        return st

    def is_complete(self) -> bool:
        return self.status()["state"] in ("SUCCEEDED", "FAILED", "KILLED")

    def is_successful(self) -> bool:
        return self.status()["state"] == "SUCCEEDED"

    def counters(self) -> Counters:
        return Counters.from_dict(self._client.call("get_counters",
                                                    self.job_id))

    def task_reports(self, kind: str = "map") -> list[dict]:
        return self._client.call("get_task_reports", self.job_id, kind)

    def kill(self) -> None:
        from tpumr.security import UserGroupInformation
        self._client.call("kill_job", self.job_id,
                          UserGroupInformation.get_current_user().user)

    def wait_for_completion(self, poll_s: float = 0.2,
                            timeout: float = 3600.0) -> dict:
        deadline = time.monotonic() + timeout
        while True:
            st = self.status()
            if st["state"] in ("SUCCEEDED", "FAILED", "KILLED"):
                return st
            if time.monotonic() > deadline:
                raise TimeoutError(f"job {self.job_id} did not finish "
                                   f"within {timeout}s: {st}")
            time.sleep(poll_s)


class JobClient:
    def __init__(self, conf: JobConf) -> None:
        self.conf = conf
        tracker = conf.get("mapred.job.tracker")
        self._client: RpcClient | None = None
        if tracker and tracker != "local":
            host, port = str(tracker).rsplit(":", 1)
            from tpumr.security import client_credentials
            secret, scope = client_credentials(conf, "jobtracker")
            # partition tolerance: a client poll rides out a master
            # restart (retry + server-side replay dedupe), so
            # wait_for_completion survives the same restarts the
            # trackers do. The submit/poll channel gets its own retry
            # key — wider than the daemon default (trackers fall back
            # to the lost-master heartbeat backoff instead; a client
            # has no such loop)
            from tpumr.core import confkeys
            self._client = RpcClient(
                host, int(port), secret=secret, scope=scope,
                retries=confkeys.get_int(conf,
                                         "tpumr.jobclient.rpc.retries"),
                backoff_ms=conf.get_int("tpumr.rpc.client.backoff.ms",
                                        200))

    @property
    def is_local(self) -> bool:
        return self._client is None

    def submit_job(self, job_conf: JobConf) -> RunningJob:
        assert self._client is not None, "local jobs use run_job()"
        conf_dict, splits = build_submission(job_conf)
        job_id = self._client.call("submit_job", conf_dict, splits)
        return RunningJob(self._client, job_id)

    def run_job(self, job_conf: JobConf) -> JobResult:
        """Submit and wait ≈ JobClient.runJob."""
        if self.is_local:
            return LocalJobRunner(self.conf).submit_job(job_conf)
        running = self.submit_job(job_conf)
        st = running.wait_for_completion()
        from tpumr.mapred.ids import JobID
        result = JobResult(job_id=JobID.parse(running.job_id),
                           successful=st["state"] == "SUCCEEDED",
                           counters=running.counters(),
                           num_maps=st["num_maps"],
                           num_reduces=st["num_reduces"],
                           error=st.get("error", ""))
        if not result.successful:
            raise RuntimeError(f"job {running.job_id} {st['state']}: "
                               f"{st.get('error', '')}")
        return result


#: client-local credentials that must NEVER ride the submitted conf:
#: the user key is a full-impersonation secret (and job confs land in
#: history files), and the key/token FILE PATHS are meaningless or
#: identity-corrupting on worker hosts (a worker resolving the
#: submitter's credential would sign DFS calls as the wrong principal)
_CLIENT_CREDENTIAL_KEYS = ("tpumr.rpc.user.key", "tpumr.rpc.user.key.file",
                           "tpumr.rpc.token.file")


def build_submission(job_conf: JobConf) -> "tuple[dict, list[dict]]":
    """The submission prep shared by the CLIENT and the master-side
    pipeline engine (one copy, or the two paths drift): device-shuffle
    collapse, format instantiation + output-spec check, split
    computation, and the credential-stripped wire conf. Returns
    ``(conf_dict, split_dicts)`` ready for the submit_job RPC."""
    from tpumr.mapred.device_shuffle import prepare_device_shuffle_job
    prepare_device_shuffle_job(job_conf)  # reduce phase → one gang task
    in_fmt = new_instance(job_conf.get_input_format(), job_conf)
    out_fmt = new_instance(job_conf.get_output_format(), job_conf)
    out_fmt.check_output_specs(job_conf)
    splits = in_fmt.get_splits(job_conf, job_conf.num_map_tasks_hint)
    return _wire_conf(job_conf), [s.to_dict() for s in splits]


def scrub_credentials(conf: dict) -> dict:
    """Drop client-local credentials from a plain conf dict — the
    pipeline path's twin of ``_wire_conf``'s stripping (graph confs
    land in the master's history journal and every stage job conf; an
    impersonation secret must never ride along)."""
    return {k: v for k, v in conf.items()
            if k not in _CLIENT_CREDENTIAL_KEYS}


def _wire_conf(job_conf: JobConf) -> dict[str, Any]:
    """Serialize the conf for submission; class OBJECTS (test-local classes)
    don't survive the wire — fail fast with a clear message
    (Configuration.set_class stores importable dotted names when it can)."""
    out: dict[str, Any] = {}
    for k, v in job_conf:
        if isinstance(v, type):
            raise ValueError(
                f"conf key {k!r} holds a class object that is not importable "
                f"by name; distributed jobs need module-level classes")
        if k in _CLIENT_CREDENTIAL_KEYS:
            continue
        out[k] = v
    if not out.get("user.name"):
        # stamp the submitting identity ≈ UGI on JobClient.submitJob —
        # the fair scheduler's default pool and history attribution use it
        from tpumr.security import UserGroupInformation
        out["user.name"] = UserGroupInformation.get_current_user().user
    return out


def run_job(conf: JobConf) -> JobResult:
    """Module-level convenience ≈ JobClient.runJob(conf)."""
    return JobClient(conf).run_job(conf)
