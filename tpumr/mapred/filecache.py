"""DistributedCache — per-job file localization with ref-counting.

≈ ``org.apache.hadoop.filecache.{DistributedCache,
TrackerDistributedCacheManager}`` (reference: src/mapred/org/apache/hadoop/
mapred/filecache/, ~2k LoC). The contract that matters to the pipes tier is
the *ordered* cache-file list: the dual-executable submission puts the CPU
binary at index 0 and the accelerator binary at index 1
(Submitter.java:349-379), and the Application picks
``localCacheFiles[runOnGPU ? 1 : 0]`` (Application.java:162-172). That
ordering is preserved bit-for-bit here (TPU instead of GPU).

Re-design notes: localization is content-addressed (sha256 of source path +
mtime + size) into a shared cache root; per-job ref counts release entries
when the job's working state is purged; executables keep their exec bit.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import stat
import threading
from typing import Any

#: conf key holding the ordered, comma-separated cache file list
#: (≈ mapred.cache.files). Entries may carry a ``#linkname`` fragment.
CACHE_FILES_KEY = "mapred.cache.files"
#: entries marked executable (localized with the exec bit set)
CACHE_EXECUTABLES_KEY = "tpumr.cache.executables"

_lock = threading.Lock()
#: (cache_root, digest) -> set of job ids holding a reference. Job-granular
#: (not per-attempt): localization runs once per task attempt but a job
#: holds exactly one reference, released when the tracker purges the job.
_refs: dict[tuple[str, str], set[str]] = {}


def add_cache_file(conf: Any, path: str, link: str | None = None,
                   executable: bool = False) -> None:
    """Append one file to the job's ordered cache list
    (≈ DistributedCache.addCacheFile)."""
    entry = f"{path}#{link}" if link else path
    cur = conf.get(CACHE_FILES_KEY, "") or ""
    conf.set(CACHE_FILES_KEY, f"{cur},{entry}" if cur else entry)
    if executable:
        ex = conf.get(CACHE_EXECUTABLES_KEY, "") or ""
        conf.set(CACHE_EXECUTABLES_KEY, f"{ex},{entry}" if ex else entry)


def get_cache_files(conf: Any) -> list[str]:
    raw = conf.get(CACHE_FILES_KEY, "") or ""
    return [e for e in raw.split(",") if e]


def _split_entry(entry: str) -> tuple[str, str]:
    if "#" in entry:
        path, link = entry.rsplit("#", 1)
    else:
        path, link = entry, os.path.basename(entry)
    return path, link


def _digest(path: str) -> str:
    st = os.stat(path)
    h = hashlib.sha256(
        f"{os.path.abspath(path)}|{st.st_mtime_ns}|{st.st_size}".encode())
    return h.hexdigest()[:24]


def get_local_cache_files(conf: Any, cache_root: str,
                          job_id: str = "") -> list[str]:
    """Localize the job's cache files (idempotent) and return their local
    paths IN LIST ORDER — the ordering contract the pipes dual-executable
    selection depends on (Application.java:162-172)."""
    out: list[str] = []
    executables = set(conf.get(CACHE_EXECUTABLES_KEY, "").split(","))
    os.makedirs(cache_root, exist_ok=True)
    for entry in get_cache_files(conf):
        path, link = _split_entry(entry)
        if not os.path.exists(path):
            raise FileNotFoundError(f"cache file missing: {path}")
        d = _digest(path)
        local_dir = os.path.join(cache_root, d)
        local = os.path.join(local_dir, link)
        with _lock:
            if not os.path.exists(local):
                os.makedirs(local_dir, exist_ok=True)
                tmp = local + ".tmp"
                shutil.copy2(path, tmp)
                os.replace(tmp, local)
            if entry in executables:
                os.chmod(local, os.stat(local).st_mode | stat.S_IXUSR
                         | stat.S_IXGRP)
            _refs.setdefault((cache_root, d), set()).add(job_id)
        out.append(local)
    return out


def release_job(conf: Any, cache_root: str, job_id: str = "") -> None:
    """Drop the job's references; entries with no remaining holders are
    deleted (≈ TrackerDistributedCacheManager.releaseCache)."""
    for entry in get_cache_files(conf):
        path, _ = _split_entry(entry)
        try:
            d = _digest(path)
        except OSError:
            continue
        with _lock:
            key = (cache_root, d)
            holders = _refs.get(key)
            if holders is not None:
                holders.discard(job_id)
                if not holders:
                    _refs.pop(key, None)
                    shutil.rmtree(os.path.join(cache_root, d),
                                  ignore_errors=True)
