from tpumr.mapred.ids import JobID, TaskAttemptID, TaskID
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.api import (
    Mapper, Reducer, Partitioner, HashPartitioner, Reporter, OutputCollector,
)
from tpumr.mapred.split import InputSplit, FileSplit
from tpumr.mapred.local_runner import LocalJobRunner, run_job

__all__ = [
    "JobID", "TaskID", "TaskAttemptID", "JobConf",
    "Mapper", "Reducer", "Partitioner", "HashPartitioner", "Reporter",
    "OutputCollector", "InputSplit", "FileSplit", "LocalJobRunner", "run_job",
]
