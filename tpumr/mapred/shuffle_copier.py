"""The shuffle copy phase: parallel, chunk-streamed, RAM-budgeted.

≈ ``ReduceCopier`` inside ``org.apache.hadoop.mapred.ReduceTask`` (reference:
src/mapred/org/apache/hadoop/mapred/ReduceTask.java — MapOutputCopier fetch
threads :659, ShuffleRamManager byte budget with in-memory vs on-disk
shuffle :1080) and the chunk-serving half of the MapOutputServlet
(TaskTracker.java:4050). Re-designed for this runtime:

- ``tpumr.shuffle.parallel.copies`` fetcher threads pull map outputs
  concurrently (the reference's mapred.reduce.parallel.copies);
- segments move as bounded CHUNKS over tracker RPC (``tpumr.shuffle.
  chunk.bytes``) — neither the serving tracker nor the copier ever holds
  an unbounded payload for one request;
- a :class:`ShuffleRamManager` budget decides in-memory vs on-disk per
  segment by its RAW (decompressed) size: small segments decompress into
  the budget, oversized or budget-starved ones stream to local disk and
  are re-read incrementally at merge time (ifile.iter_chunked_segment),
  so reduce-side memory is bounded by budget + copies × chunk.

The copy phase owns a BACKGROUND IN-MEMORY MERGER
(:class:`ShuffleMergeManager` ≈ ReduceTask's InMemFSMergeThread): once the
memory segments accumulated by fetchers cross
``mapred.job.shuffle.merge.percent`` of the ShuffleRamManager budget, the
merger thread k-way merges them (running the job's combiner when one is
configured) into ONE sorted on-disk run and releases their reservations —
so fetchers keep landing in memory mid-copy instead of degrading to one
disk file per segment once the budget fills. A budget-starved fetcher
waits BOUNDED for an in-flight merge to free reservations
(``tpumr.shuffle.merge.reserve.wait.ms``) and only then falls back to a
per-segment disk spill — the reference blocks unboundedly here; the bound
keeps the no-deadlock property of the earlier design. ``copy_all()``
returns live memory segments plus a handful of pre-merged sorted runs.

Lost-map-output recovery (the "too many fetch failures" protocol,
≈ ReduceTask's fetch-failure notification up the umbilical): when the
caller wires an ``on_fetch_failure`` callback, a failing map location is
never terminal for the reduce. Each source lands in a per-address
PENALTY BOX (capped, jittered exponential backoff — a recovering tracker
is never hit by a thundering herd); after
``tpumr.shuffle.fetch.retries.per.source`` failures against one location
the failure is reported up (the master counts distinct reducers and
re-executes the map) and the cached location is invalidated so the
re-run map's NEW address is picked up mid-shuffle from refreshed
completion events — the copy phase never restarts.
"""

from __future__ import annotations

import os
import queue
import random
import tempfile
import threading
import time
from typing import Any, Callable, Iterable, Iterator

from tpumr.core.counters import TaskCounter
from tpumr.core import confkeys
from tpumr.io import ifile

#: source protocol: fetch_chunk(map_index, partition, offset) -> dict with
#: "data" (payload bytes from offset), "total" (payload length), "raw"
#: (decompressed segment length), "codec".
ChunkFetch = Callable[[int, int, int], dict]


def shuffle_metrics():
    """The process-wide ``shuffle`` metrics source: whole-segment fetch
    latency and size distributions plus a failure counter, shared by
    every reduce attempt in this process. Published by whichever tracker
    claims the source (tasktracker.py); fetch p95 is the series the
    ROADMAP's shuffle wire-path work regresses against."""
    from tpumr.metrics.core import process_registry
    from tpumr.metrics.histogram import BYTES
    reg = process_registry("shuffle")
    # names carry the source prefix so a direct tracker scrape and the
    # master's cluster merge agree on one metric name (the source is a
    # label on the tracker, "cluster" on the master)
    reg.histogram("shuffle_fetch_seconds")
    # transferred (post-wire-codec) bytes — what actually crossed the
    # network; shuffle_fetch_raw_bytes is the decompressed size, so the
    # wire/raw pair separates compression ratio from copy throughput
    reg.histogram("shuffle_fetch_bytes", BYTES)
    reg.histogram("shuffle_fetch_wire_bytes", BYTES)
    reg.histogram("shuffle_fetch_raw_bytes", BYTES)
    return reg


class ShuffleRamManager:
    """In-memory shuffle byte budget (≈ ReduceTask.java:1080). Accounting
    is in RAW segment bytes — what actually sits in memory after
    decompression. ``max_single`` mirrors the reference's rule that one
    segment may claim at most a fraction of the whole budget."""

    def __init__(self, budget_bytes: int,
                 max_single_frac: float = 0.25) -> None:
        self.budget = max(0, int(budget_bytes))
        self.max_single = int(self.budget * max_single_frac)
        self._used = 0
        self._lock = threading.Lock()
        self._freed = threading.Condition(self._lock)

    @property
    def used(self) -> int:
        return self._used

    def try_reserve(self, nbytes: int) -> bool:
        """Claim budget for one segment, or refuse (caller spills to
        disk, or waits via :meth:`reserve_wait` when a background merge
        may free budget). Never blocks."""
        if nbytes > self.max_single:
            return False
        with self._lock:
            if self._used + nbytes > self.budget:
                return False
            self._used += nbytes
            return True

    def reserve_wait(self, nbytes: int, keep_waiting: "Callable[[], bool]",
                     timeout_s: float) -> bool:
        """Bounded-blocking reserve: wait for budget while
        ``keep_waiting()`` reports a concurrent merge may still free
        some, up to ``timeout_s``. The reference blocks a fetcher here
        UNBOUNDEDLY (its merge thread always frees budget eventually);
        the bound keeps this runtime deadlock-free even if the merger
        stalls — the caller just falls back to a disk spill."""
        if nbytes > self.max_single:
            return False
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._freed:
            while True:
                if self._used + nbytes <= self.budget:
                    self._used += nbytes
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not keep_waiting():
                    return False
                # short waits: keep_waiting() can flip false without a
                # release ever being notified
                self._freed.wait(min(remaining, 0.05))

    def release(self, nbytes: int) -> None:
        with self._freed:
            self._used = max(0, self._used - nbytes)
            self._freed.notify_all()


class Segment:
    """One map output's partition segment, iterable as (kbytes, vbytes)."""

    #: raw (decompressed) size, for accounting/diagnostics
    raw_length = 0
    #: bytes that actually crossed the wire fetching this segment
    #: (post wire-codec compression); 0 for purely local segments
    wire_length = 0
    in_memory = False

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemorySegment(Segment):
    """Decompressed segment held under a ShuffleRamManager reservation.
    ``reserved`` is the amount actually claimed from the manager (the
    index-reported raw size) — released EXACTLY, so a writer/index skew
    between reported and actual decompressed size can never drift the
    budget accounting."""

    in_memory = True

    def __init__(self, raw: bytes, ram: ShuffleRamManager | None,
                 reserved: int | None = None) -> None:
        self._raw: bytes | None = raw
        self.raw_length = len(raw)
        self._reserved = self.raw_length if reserved is None else reserved
        self._ram = ram

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        if self._raw is None:
            raise ValueError("segment closed")
        return ifile.iter_segment(self._raw)

    def close(self) -> None:
        if self._raw is not None and self._ram is not None:
            self._ram.release(self._reserved)
        self._raw = None


class DiskSegment(Segment):
    """Compressed payload spilled to a local file; records stream out
    through the incremental decompressor at merge time."""

    def __init__(self, path: str, codec: str, raw_length: int,
                 offset: int = 0, length: int | None = None,
                 owns_file: bool = True) -> None:
        self.path = path
        self.codec = codec
        self.raw_length = raw_length
        self.offset = offset
        self.length = (length if length is not None
                       else os.path.getsize(path) - offset)
        self._owns = owns_file

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        return ifile.iter_chunked_segment(
            ifile.file_region_chunks(self.path, self.offset, self.length),
            self.codec)

    def close(self) -> None:
        if self._owns:
            try:
                os.unlink(self.path)
            except OSError:
                pass


def spill_region_segment(path: str, index: dict,
                         partition: int) -> DiskSegment:
    """A segment view straight over an existing local spill file (the
    LocalJobRunner / same-host path): zero copy, streamed at merge time.
    The spill file is owned by the map side — never deleted here."""
    off, raw_len, part_len = index["partitions"][partition]
    # skip the 4-byte length prefix; the payload is part_len - 4 bytes
    return DiskSegment(path, index.get("codec", "none"), raw_len,
                       offset=off + 4, length=part_len - 4,
                       owns_file=False)


class LocalSegmentSource:
    """Segment source over same-process map outputs (LocalJobRunner):
    replaces the old list-materializing local_fetch_factory — Weak #6's
    unbounded reduce-side memory goes away because nothing is loaded
    until the merge streams it."""

    def __init__(self, map_outputs: "list[tuple[str, dict]]") -> None:
        self._outputs = map_outputs

    def segments(self, partition: int) -> "list[Segment]":
        out: list[Segment] = []
        for path, index in self._outputs:
            if not path:
                continue
            out.append(spill_region_segment(path, index, partition))
        return out


class PenaltyBox:
    """Per-source backoff state (≈ the reference ReduceCopier's
    penaltyBox of fetch-failed hosts): each failure against a location
    doubles its hold-off up to ``cap_s``, jittered to 50–100% of nominal
    so fetchers never re-converge on a recovering tracker in lockstep.
    A success clears the location's strikes entirely."""

    def __init__(self, base_s: float, cap_s: float) -> None:
        self.base_s = max(0.0, base_s)
        self.cap_s = max(self.base_s, cap_s)
        self._lock = threading.Lock()
        self._strikes: dict[str, int] = {}
        self._until: dict[str, float] = {}

    def punish(self, key: str) -> float:
        """Record one failure; returns the jittered hold-off seconds.
        Hold-offs are MONOTONIC stamps: a wall-clock step mid-shuffle
        must neither spring every penalized source free at once nor
        freeze them in the box."""
        with self._lock:
            strikes = self._strikes.get(key, 0) + 1
            self._strikes[key] = strikes
            delay = min(self.cap_s, self.base_s * (2 ** (strikes - 1)))
            delay *= 0.5 + random.random() * 0.5
            self._until[key] = max(self._until.get(key, 0.0),
                                   time.monotonic() + delay)
            return delay

    def until(self, key: str) -> float:
        """Earliest time (monotonic clock) this source should be fetched
        from again."""
        with self._lock:
            return self._until.get(key, 0.0)

    def clear(self, key: str) -> None:
        with self._lock:
            self._strikes.pop(key, None)
            self._until.pop(key, None)

    def active(self) -> int:
        """How many sources are currently serving a penalty (gauge)."""
        now = time.monotonic()
        with self._lock:
            return sum(1 for t in self._until.values() if t > now)


class ShuffleMergeManager:
    """Background in-memory merger thread (≈ ReduceTask's
    InMemFSMergeThread): fetchers hand fully-copied
    :class:`MemorySegment`\\ s over via :meth:`offer`; once their bytes
    cross ``mapred.job.shuffle.merge.percent`` of the RAM budget (or a
    budget-starved fetcher calls :meth:`request_merge`), the merger
    k-way merges them — running the job's combiner when configured —
    into ONE sorted on-disk run (``ifile`` format via
    ``io.merger.write_run``) and closes the inputs, releasing their
    reservations mid-copy. Batches merge in map-index order so the
    merged run's equal-key tiebreak is deterministic.

    A second, disk-side thread (≈ ReduceTask's LocalFSMerger) folds
    accumulated per-segment disk spills into sorted runs whenever
    ``io.sort.factor`` of them exist — the copy phase's wire waits pay
    for the rewrite, and the final merge stays a single pass instead of
    re-reading everything through bounded-fan-in intermediate passes."""

    def __init__(self, conf: Any, ram: ShuffleRamManager, spill_dir: str,
                 reporter: Any, trace_ctx: Any) -> None:
        self.conf = conf
        self.ram = ram
        self.spill_dir = spill_dir
        self.reporter = reporter
        self._trace_ctx = trace_ctx
        pct = confkeys.get_float(conf, "mapred.job.shuffle.merge.percent")
        self.threshold = max(1, int(ram.budget * pct))
        get_cmp = getattr(conf, "get_output_key_comparator", None)
        self._sort_key = (get_cmp().sort_key if get_cmp is not None
                          else None)
        get_comb = getattr(conf, "get_combiner_class", None)
        self.combiner_cls = get_comb() if get_comb is not None else None
        self.codec = getattr(conf, "compress_map_output", "none")
        self._cond = threading.Condition()
        self._pending: "list[tuple[int, MemorySegment]]" = []
        self._pending_bytes = 0
        self._merged_ids: "set[int]" = set()
        self._runs: "list[Any]" = []
        self._requested = False
        self._busy = False
        self._closed = False
        self._error: "Exception | None" = None
        self._thread: "threading.Thread | None" = None
        self.inmem_merges = 0
        self.inmem_merge_segments = 0
        #: disk side (≈ LocalFSMerger): per-segment spills accumulate
        #: here; once ``io.sort.factor`` of them exist, a second
        #: background thread folds them into one sorted run. The work
        #: overlaps fetchers' wire waits, so the end-of-copy merge stays
        #: single-pass instead of paying bounded-fan-in rewrite passes.
        self.disk_factor = max(2, confkeys.get_int(conf, "io.sort.factor"))
        self._pending_disk: "list[tuple[int, Segment]]" = []
        self._disk_thread: "threading.Thread | None" = None
        self.disk_merges = 0
        self.disk_merge_segments = 0

    # ------------------------------------------------------- fetcher side

    def offer(self, map_index: int, seg: MemorySegment) -> bool:
        """Take ownership of a fully-fetched memory segment. Returns
        False (caller keeps ownership) after close/abort or once a merge
        error killed the merger — nothing would ever merge it."""
        with self._cond:
            if self._closed or self._error is not None:
                return False
            self._pending.append((map_index, seg))
            self._pending_bytes += seg.raw_length
            if self._pending_bytes >= self.threshold \
                    and len(self._pending) >= 2:
                self._requested = True
                self._cond.notify_all()
            self._ensure_thread()
            return True

    def offer_disk(self, map_index: int, seg: Segment) -> bool:
        """Take ownership of a landed per-segment disk spill. Once
        ``io.sort.factor`` spills accumulate, the disk-merge thread
        folds the first ``factor`` (in map-index order, for a
        deterministic equal-key tiebreak) into one sorted run. Returns
        False after close/error — the caller keeps ownership."""
        with self._cond:
            if self._closed or self._error is not None:
                return False
            self._pending_disk.append((map_index, seg))
            if len(self._pending_disk) >= self.disk_factor:
                self._ensure_disk_thread()
                self._cond.notify_all()
            return True

    def request_merge(self) -> None:
        """A budget-starved fetcher asks for whatever has accumulated
        to be merged out of memory now, below the watermark."""
        with self._cond:
            if self._closed or self._error is not None \
                    or len(self._pending) < 2:
                return
            self._requested = True
            self._ensure_thread()
            self._cond.notify_all()

    def busy_or_pending(self) -> bool:
        """Is budget plausibly about to be freed? (the fetcher's
        keep-waiting predicate for ``ShuffleRamManager.reserve_wait``).
        A stored merge error means the merger thread is DEAD — budget is
        never coming, so fetchers must fall through to disk immediately
        instead of burning the full reserve-wait timeout per fetch."""
        with self._cond:
            return self._error is None and (self._busy or self._requested)

    # ------------------------------------------------------- merger side

    def _ensure_thread(self) -> None:
        # lazily started (under self._cond) so a copier that never
        # copies doesn't leak an idle thread
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="shuffle-inmem-merger",
                                            daemon=True)
            self._thread.start()

    def _ensure_disk_thread(self) -> None:
        # separate from the in-memory loop: a long disk merge must not
        # delay the merges that free ShuffleRamManager budget
        if self._disk_thread is None:
            self._disk_thread = threading.Thread(
                target=self._disk_loop, name="shuffle-disk-merger",
                daemon=True)
            self._disk_thread.start()

    def _disk_loop(self) -> None:
        from tpumr.core import tracing
        with tracing.activate_captured(self._trace_ctx):
            while True:
                with self._cond:
                    while (not self._closed and
                           len(self._pending_disk) < self.disk_factor):
                        self._cond.wait(0.1)
                    if self._closed:
                        return
                    self._pending_disk.sort(key=lambda p: p[0])
                    batch = [s for _, s in
                             self._pending_disk[:self.disk_factor]]
                    del self._pending_disk[:self.disk_factor]
                try:
                    self._merge_disk_batch(batch)
                except Exception as e:  # noqa: BLE001 — surfaced at finish
                    for seg in batch:
                        seg.close()
                    with self._cond:
                        self._error = e
                        self._merged_ids.update(id(s) for s in batch)
                        self._cond.notify_all()
                    return

    def _merge_disk_batch(self, batch: "list[Segment]") -> None:
        from tpumr.core import tracing
        from tpumr.io import merger as merge_engine
        raw_bytes = sum(s.raw_length for s in batch)
        with tracing.span("shuffle:disk_merge", segments=len(batch),
                          raw_bytes=raw_bytes) as sp:
            if raw_bytes <= 2 * self.ram.budget:
                # a factor-sized batch of budget-scale spills: a
                # transient full materialization (NOT reserved — it is
                # bounded by construction) buys the Timsort-galloping
                # merge, keeping this thread's GIL draw small enough to
                # hide inside fetchers' wire waits
                merged = ifile.merge_sorted_inmem(batch, self._sort_key)
                run = merge_engine.write_run(merged, self.spill_dir,
                                             prefix="disk-merge")
            else:
                # oversized spills (> max_single each): streaming heap
                # merge + bounded-memory run writer
                merged = ifile.merge_sorted(batch, self._sort_key)
                run = merge_engine.write_run_streaming(
                    merged, self.spill_dir, prefix="disk-merge")
            if sp is not None:
                sp.set(run_bytes=run.length, records=run.records)
        for seg in batch:
            seg.close()
        with self._cond:
            self._runs.append(run)
            self._merged_ids.update(id(s) for s in batch)
            self.disk_merges += 1
            self.disk_merge_segments += len(batch)
        if self.reporter is not None:
            self.reporter.incr_counter(
                TaskCounter.FRAMEWORK_GROUP,
                TaskCounter.SHUFFLE_DISK_MERGES, 1)
            self.reporter.incr_counter(
                TaskCounter.FRAMEWORK_GROUP,
                TaskCounter.SHUFFLE_DISK_MERGE_SEGMENTS, len(batch))

    def _loop(self) -> None:
        from tpumr.core import tracing
        with tracing.activate_captured(self._trace_ctx):
            while True:
                with self._cond:
                    while not self._closed and not self._requested:
                        self._cond.wait(0.1)
                    if self._requested and len(self._pending) >= 2:
                        # map-index order: deterministic equal-key
                        # tiebreak no matter the fetch completion order
                        batch = [s for _, s in sorted(self._pending,
                                                      key=lambda p: p[0])]
                        self._pending = []
                        self._pending_bytes = 0
                        self._requested = False
                        self._busy = True
                    elif self._closed:
                        return
                    else:
                        self._requested = False
                        continue
                try:
                    self._merge_batch(batch)
                except Exception as e:  # noqa: BLE001 — surfaced at finish
                    for seg in batch:
                        seg.close()   # release reservations regardless
                    with self._cond:
                        self._error = e
                        self._busy = False
                        self._merged_ids.update(id(s) for s in batch)
                        self._cond.notify_all()
                    return
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def _merge_batch(self, batch: "list[MemorySegment]") -> None:
        from tpumr.core import tracing
        from tpumr.io import merger as merge_engine
        raw_bytes = sum(s.raw_length for s in batch)
        with tracing.span("shuffle:mem_merge", segments=len(batch),
                          raw_bytes=raw_bytes) as sp:
            # batches are budget-bounded and fully resident, so the
            # materialized Timsort-galloping merge applies (~2× the
            # lazy heap merge, byte-identical order)
            merged: "Iterable[tuple[bytes, bytes]]" = \
                ifile.merge_sorted_inmem(batch, self._sort_key)
            if self.combiner_cls is not None:
                from tpumr.mapred.combine import combined_stream
                merged = combined_stream(self.conf, self.combiner_cls,
                                         self._sort_key, merged,
                                         self.reporter)
            run = merge_engine.write_run(merged, self.spill_dir,
                                         codec=self.codec,
                                         prefix="inmem-merge")
            if sp is not None:
                sp.set(run_bytes=run.length, records=run.records)
        for seg in batch:
            seg.close()   # HERE the budget frees — mid-copy, not at end
        with self._cond:
            self._runs.append(run)
            self._merged_ids.update(id(s) for s in batch)
            self.inmem_merges += 1
            self.inmem_merge_segments += len(batch)
        if self.reporter is not None:
            self.reporter.incr_counter(
                TaskCounter.FRAMEWORK_GROUP,
                TaskCounter.SHUFFLE_INMEM_MERGES, 1)
            self.reporter.incr_counter(
                TaskCounter.FRAMEWORK_GROUP,
                TaskCounter.SHUFFLE_INMEM_MERGE_SEGMENTS, len(batch))

    # ---------------------------------------------------------- lifecycle

    def finish(self) -> "list[Any]":
        """Stop the merger (honoring one outstanding requested merge)
        and return the merged runs. Raises a merge error if one was
        stored — the copy phase must not return half-merged state."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            threads = [t for t in (self._thread, self._disk_thread)
                       if t is not None]
        for t in threads:
            t.join()
        if self._error is not None:
            raise self._error
        # unmerged disk leftovers stay out of _merged_ids, so the copier
        # returns them as ordinary live segments
        return list(self._runs)

    @property
    def merged_ids(self) -> "set[int]":
        with self._cond:
            return set(self._merged_ids)

    def abort(self) -> None:
        """Failure-path teardown: close pending segments (releasing
        budget) and delete merged runs."""
        with self._cond:
            self._closed = True
            self._requested = False
            self._cond.notify_all()
            threads = [t for t in (self._thread, self._disk_thread)
                       if t is not None]
        for t in threads:
            t.join(timeout=30)
        with self._cond:
            pending, self._pending = self._pending, []
            pending_disk, self._pending_disk = self._pending_disk, []
            self._pending_bytes = 0
            runs, self._runs = self._runs, []
        for _, seg in pending:
            seg.close()
        for _, seg in pending_disk:
            seg.close()
        for run in runs:
            run.close()


class ShuffleCopier:
    """Run the copy phase: ``copy_all()`` returns every map's segment for
    this reduce's partition, fetched by a pool of copier threads."""

    def __init__(self, conf: Any, source: ChunkFetch, num_maps: int,
                 partition: int, spill_dir: str,
                 reporter: Any = None,
                 on_fetch_failure: "Callable[[int, str], None] | None"
                 = None) -> None:
        self.conf = conf
        self.source = source
        self.num_maps = num_maps
        self.partition = partition
        self.spill_dir = spill_dir
        self.reporter = reporter
        #: fetch-failure report seam (reduce → tracker → master): called
        #: as ``on_fetch_failure(map_index, map_attempt_id)`` after
        #: ``retries.per.source`` failures against one location. When
        #: None (local/legacy sources) a persistently failing fetch is
        #: terminal after the local retries, as before.
        self.on_fetch_failure = on_fetch_failure
        self.parallel = max(1, confkeys.get_int(
            conf, "tpumr.shuffle.parallel.copies"))
        ram_mb = confkeys.get_float(conf, "tpumr.shuffle.ram.mb")
        pct = confkeys.get_float(
            conf, "mapred.job.shuffle.input.buffer.percent")
        self.ram = ShuffleRamManager(int(ram_mb * 1024 * 1024 * pct))
        self.retries = confkeys.get_int(conf, "tpumr.shuffle.copy.retries")
        self.backoff_s = confkeys.get_float(
            conf, "tpumr.shuffle.copy.backoff.ms") / 1000.0
        self.backoff_cap_s = confkeys.get_float(
            conf, "tpumr.shuffle.copy.backoff.max.ms") / 1000.0
        #: failures against ONE map location before a fetch-failure
        #: report goes up the umbilical (≈ maxFetchFailuresBeforeReporting)
        self.retries_per_source = max(1, confkeys.get_int(
            conf, "tpumr.shuffle.fetch.retries.per.source"))
        #: hard ceiling of total failures for one map before the copy
        #: phase gives up terminally even in protocol mode — bounds a
        #: shuffle against a map the master never manages to re-run
        self.max_fetch_failures = max(1, confkeys.get_int(
            conf, "tpumr.shuffle.fetch.max.failures"))
        self.penalty_box = PenaltyBox(self.backoff_s, self.backoff_cap_s)
        # blocked-on-location waits count as liveness for the tracker's
        # hung-task reaper: a fetcher parked in the locator's poll loop
        # (waiting for a lost map's re-run to publish) is waiting, not
        # hung (≈ Hadoop reduces ticking reporter.progress per fetch
        # iteration). Duck-typed: only the tracker/child MapLocator has
        # the on_wait seam.
        if reporter is not None:
            locate = getattr(source, "locate", None)
            if locate is not None and hasattr(locate, "on_wait"):
                locate.on_wait = reporter.keepalive
        #: observability: how many segments went to disk vs memory
        #: (mutated by parallel workers — guarded by _stats_lock)
        self.spilled_to_disk = 0
        self.copied_in_memory = 0
        self.fetch_failures = 0
        self.fetch_failures_reported = 0
        self._stats_lock = threading.Lock()
        self._map_failures: dict[int, int] = {}
        self._src_failures: dict[tuple[int, str], int] = {}
        # built on the TASK thread: snapshot its ambient trace context so
        # fetch spans recorded by the worker pool nest under the reduce's
        # run span (core/tracing.py; None when tracing is off)
        from tpumr.core import tracing
        self._trace_ctx = tracing.capture()
        #: background in-memory merger (≈ InMemFSMergeThread); None when
        #: disabled or pointless (no budget, single map)
        self.merger: "ShuffleMergeManager | None" = None
        if (confkeys.get_boolean(conf, "tpumr.shuffle.merge.enabled")
                and self.ram.budget > 0 and num_maps >= 2):
            self.merger = ShuffleMergeManager(conf, self.ram, spill_dir,
                                              reporter, self._trace_ctx)
        #: how long a budget-starved fetcher waits for an in-flight
        #: background merge to free reservations before spilling to disk
        self.reserve_wait_s = confkeys.get_float(
            conf, "tpumr.shuffle.merge.reserve.wait.ms") / 1000.0
        #: size-aware fetch ordering: completion events advertise each
        #: map's output bytes (TaskStatus.output_bytes); among equally-
        #: ready pending fetches the LARGEST advertised output pops
        #: first, so the long-pole transfer overlaps the most remaining
        #: copy work instead of landing last. Advisory — an unknown
        #: size (0) just sorts behind known ones, never blocks a fetch.
        self.size_priority = confkeys.get_boolean(
            conf, "tpumr.shuffle.size.priority")

    # ------------------------------------------------------------ one map

    def _copy_one(self, map_index: int) -> Segment:
        from tpumr.core import tracing
        reg = shuffle_metrics()
        t0 = time.monotonic()
        with tracing.span("shuffle:fetch", map_index=map_index,
                          addr=self._addr_of(map_index)) as s:
            try:
                seg = self._copy_one_inner(map_index)
            except Exception:
                # failed rounds are part of the latency story too — a
                # fetcher burning 2s per failure against a dead source
                # shows up in the distribution, not just the counter
                reg.incr("shuffle_fetch_errors")
                reg.histogram("shuffle_fetch_seconds").observe(
                    time.monotonic() - t0)
                raise
            reg.histogram("shuffle_fetch_seconds").observe(
                time.monotonic() - t0)
            self._observe_seg(reg, seg)
            if s is not None:
                s.set(raw_bytes=seg.raw_length,
                      wire_bytes=seg.wire_length,
                      in_memory=seg.in_memory)
            return seg

    @staticmethod
    def _observe_seg(reg, seg: Segment) -> None:
        # fetch_bytes reports TRANSFERRED bytes (it used to report raw —
        # with a wire codec those diverge); wire/raw land in their own
        # pair so ratio and throughput stay separable on /metrics
        wire = seg.wire_length or seg.raw_length
        reg.histogram("shuffle_fetch_bytes").observe(wire)
        reg.histogram("shuffle_fetch_wire_bytes").observe(wire)
        reg.histogram("shuffle_fetch_raw_bytes").observe(seg.raw_length)

    def _copy_one_inner(self, map_index: int) -> Segment:
        from tpumr.utils.fi import maybe_fail
        maybe_fail("shuffle.fetch", self.conf)
        maybe_fail(f"shuffle.fetch.m{map_index}", self.conf)
        fetch_chunks = getattr(self.source, "fetch_chunks", None)
        if fetch_chunks is not None:
            # pipelined path: the source resolves the serving address
            # ONCE, leases one pooled connection, and keeps N chunk
            # requests in flight — re-resolution happens only on the
            # next retry round after a failure, so a mid-fetch OBSOLETE
            # fold can't flip a healthy in-flight stream
            chunks = fetch_chunks(map_index, self.partition)
            try:
                first = next(iter(chunks))
            except StopIteration:
                raise EOFError(f"shuffle source returned no chunks for "
                               f"map {map_index}") from None
            return self._materialize(map_index, first, chunks,
                                     park_on_merger=False)
        first = self.source(map_index, self.partition, 0)

        def rest() -> "Iterator[dict]":
            got = len(first["data"])
            total = int(first["total"])
            while got < total:
                nxt = self.source(map_index, self.partition, got)
                if not nxt["data"]:
                    raise EOFError(
                        f"shuffle source returned empty chunk at "
                        f"{got}/{total} for map {map_index}")
                yield nxt
                got += len(nxt["data"])

        return self._materialize(map_index, first, rest(),
                                 park_on_merger=True)

    def _materialize(self, map_index: int, first: dict,
                     rest: "Iterator[dict]", *,
                     park_on_merger: bool) -> Segment:
        """Land one segment from a decoded first chunk + an iterator of
        the remaining decoded chunks: reserve RAM budget (or spill to
        disk), account wire vs raw bytes, verify the byte count.

        ``park_on_merger`` keeps the legacy budget-starved behavior
        (bounded ``reserve_wait`` gated on the background merger) for
        plain chunk sources. The pipelined/batched paths pass False:
        gating fetch throughput on merge throughput is exactly how the
        copy-dominated regime lost end-to-end — they nudge the merger,
        take whatever budget exists right now, and otherwise stream to
        local disk at disk speed."""
        total = int(first["total"])
        raw = int(first.get("raw", total))
        codec = first.get("codec", "none")
        parts = [first["data"]]
        got = len(first["data"])
        wire = int(first.get("wire_len", got))

        reserved = self.ram.try_reserve(raw)
        if not reserved and self.merger is not None:
            # budget full: ask the merger to fold the accumulated memory
            # segments into a disk run and free their reservations
            self.merger.request_merge()
            if park_on_merger:
                reserved = self.ram.reserve_wait(
                    raw, self.merger.busy_or_pending, self.reserve_wait_s)
            else:
                reserved = self.ram.try_reserve(raw)
        try:
            if reserved:
                # in-memory: drain chunks, decompress into the budget
                try:
                    for nxt in rest:
                        parts.append(nxt["data"])
                        got += len(nxt["data"])
                        wire += int(nxt.get("wire_len", len(nxt["data"])))
                    if got != total:
                        raise EOFError(
                            f"shuffle stream ended at {got}/{total} for "
                            f"map {map_index}")
                    from tpumr.io.compress import get_codec
                    raw_bytes = get_codec(codec).decompress(b"".join(parts))
                    with self._stats_lock:
                        self.copied_in_memory += 1
                    seg: Segment = MemorySegment(raw_bytes, self.ram,
                                                 reserved=raw)
                except BaseException:
                    self.ram.release(raw)
                    raise
            else:
                # on-disk: stream chunks straight to a local spill file
                fd, path = tempfile.mkstemp(
                    prefix=f"shuffle-m{map_index}-", suffix=".seg",
                    dir=self.spill_dir)
                try:
                    with os.fdopen(fd, "wb") as f:
                        for p in parts:
                            f.write(p)
                        for nxt in rest:
                            f.write(nxt["data"])
                            got += len(nxt["data"])
                            wire += int(nxt.get("wire_len",
                                                len(nxt["data"])))
                    if got != total:
                        raise EOFError(
                            f"shuffle stream ended at {got}/{total} for "
                            f"map {map_index}")
                except BaseException:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    raise
                with self._stats_lock:
                    self.spilled_to_disk += 1
                seg = DiskSegment(path, codec, raw)
        finally:
            close = getattr(rest, "close", None)
            if close is not None:
                close()   # abandoned pipelined window: release the lease
        seg.wire_length = wire
        return seg

    # ------------------------------------------------- batched fetching

    def _coalesce(self, work: "queue.Queue[tuple]",
                  first_map: int) -> "list[int]":
        """Group queued maps served by ``first_map``'s source address
        into one batched round (the wire-level half of
        :mod:`tpumr.mapred.fetch_batcher`). Only in protocol mode
        (``on_fetch_failure`` wired): a batch-member failure re-enters
        the queue via the penalty box, which IS the retry loop there —
        the legacy in-line-retries path stays per-map."""
        if self.on_fetch_failure is None \
                or getattr(self.source, "fetch_batch", None) is None:
            return [first_map]
        limit = int(getattr(self.source, "batch_segments", 1))
        if limit <= 1:
            return [first_map]
        from tpumr.mapred.fetch_batcher import coalesce_shuffle_fetches
        addr = self._addr_of(first_map)

        def ready_now(ready: float, m: int) -> bool:
            hold = max(ready, self._penalized_until(m))
            return hold <= time.monotonic()

        return coalesce_shuffle_fetches(
            first_map, addr, work, self._addr_of, ready_now, limit)

    def _copy_batch(self, members: "list[int]") \
            -> "list[tuple[int, Segment | None, Exception | None]]":
        """One ``get_map_outputs_batch`` round against a single source:
        many small segments in one response frame. Returns a
        ``(map_index, segment, error)`` triple per member — segment set
        on success, error set on a per-member failure (fetch-failure
        protocol), NEITHER set when the server omitted the entry under
        its byte budget (just requeue it)."""
        from tpumr.core import tracing
        from tpumr.utils.fi import maybe_fail
        reg = shuffle_metrics()
        t0 = time.monotonic()
        out: "list[tuple[int, Segment | None, Exception | None]]" = []
        ask: "list[int]" = []
        for m in members:
            try:
                # the per-map chaos seam fires per MEMBER, client-side,
                # so one poisoned map fails alone while siblings batch
                maybe_fail(f"shuffle.fetch.m{m}", self.conf)
                ask.append(m)
            except Exception as e:  # noqa: BLE001 — fi seam
                out.append((m, None, e))
        if not ask:
            return out
        with tracing.span("shuffle:fetch_batch", members=len(ask),
                          addr=self._addr_of(ask[0])) as sp:
            try:
                maybe_fail("shuffle.fetch", self.conf)
                entries = self.source.fetch_batch(ask, self.partition)
            except Exception as e:  # noqa: BLE001 — whole round failed
                reg.incr("shuffle_fetch_errors")
                reg.histogram("shuffle_fetch_seconds").observe(
                    time.monotonic() - t0)
                out.extend((m, None, e) for m in ask)
                return out
            reg.histogram("shuffle_fetch_seconds").observe(
                time.monotonic() - t0)
            by_map = {int(ent["map_index"]): ent for ent in entries}
            landed = 0
            for m in ask:
                ent = by_map.get(m)
                if ent is None:
                    out.append((m, None, None))   # budget-omitted
                    continue
                if ent.get("error"):
                    # per-entry failure rode back inside a healthy
                    # batch: exactly this map enters the fetch-failure
                    # protocol, its batch-mates landed
                    out.append((m, None, RuntimeError(
                        f"shuffle source error for map {m}: "
                        f"{ent['error']}")))
                    continue
                try:
                    seg = self._land_batch_entry(m, ent)
                except Exception as e:  # noqa: BLE001
                    out.append((m, None, e))
                    continue
                self._observe_seg(reg, seg)
                landed += 1
                out.append((m, seg, None))
            if sp is not None:
                sp.set(landed=landed)
        return out

    def _land_batch_entry(self, map_index: int, ent: dict) -> Segment:
        """Materialize one batch entry; an oversized segment arrives as
        a payload PREFIX and continues over the chunked stream."""
        total = int(ent["total"])
        if len(ent["data"]) < total:
            chunks = self.source.fetch_chunks(
                map_index, self.partition, start=len(ent["data"]),
                total=total)
            return self._materialize(map_index, ent, chunks,
                                     park_on_merger=False)
        return self._materialize(map_index, ent, iter(()),
                                 park_on_merger=False)

    def _local_backoff_s(self, attempt: int) -> float:
        """Capped, jittered exponential backoff for in-line retries:
        the raw ``base * 2**attempt`` was unbounded AND synchronized
        across fetchers — every copier that failed together retried
        together, a thundering herd onto a recovering tracker."""
        delay = min(self.backoff_cap_s, self.backoff_s * (2 ** attempt))
        return delay * (0.5 + random.random() * 0.5)

    def _copy_with_retries(self, map_index: int) -> Segment:
        last: Exception | None = None
        for attempt in range(self.retries + 1):
            try:
                return self._copy_one(map_index)
            except Exception as e:  # noqa: BLE001 — fetch failure is data
                last = e
                if attempt < self.retries:
                    time.sleep(self._local_backoff_s(attempt))
        raise RuntimeError(
            f"shuffle fetch of map {map_index} partition {self.partition} "
            f"failed after {self.retries + 1} attempts: {last}") from last

    # ------------------------------------------- fetch-failure protocol

    def _source_hook(self, name: str, map_index: int, default: Any = None):
        fn = getattr(self.source, name, None)
        if fn is None:
            return default
        try:
            return fn(map_index)
        except Exception:  # noqa: BLE001 — hooks are advisory
            return default

    def _addr_of(self, map_index: int) -> str:
        """The map's currently-resolved serving address (penalty-box
        key); falls back to a per-map key for sources without one."""
        return self._source_hook("addr_of", map_index) or f"map-{map_index}"

    def _penalized_until(self, map_index: int) -> float:
        return self.penalty_box.until(self._addr_of(map_index))

    def _note_success(self, map_index: int) -> None:
        self.penalty_box.clear(self._addr_of(map_index))
        with self._stats_lock:
            self._map_failures.pop(map_index, None)
            # per-source strikes too — otherwise they'd accumulate
            # across long-separated transient blips until the modulo
            # cadence fired a spurious report against a healthy source
            for k in [k for k in self._src_failures if k[0] == map_index]:
                del self._src_failures[k]

    def _note_failure(self, map_index: int) -> "float | None":
        """Account one failed fetch round. Returns the retry hold-off in
        seconds, or None when the failure must be terminal (no report
        callback wired, or the per-map failure ceiling was hit)."""
        if self.on_fetch_failure is None:
            return None
        addr = self._addr_of(map_index)
        with self._stats_lock:
            total = self._map_failures.get(map_index, 0) + 1
            self._map_failures[map_index] = total
            key = (map_index, addr)
            per_src = self._src_failures.get(key, 0) + 1
            self._src_failures[key] = per_src
            self.fetch_failures += 1
        if total >= self.max_fetch_failures:
            return None
        delay = self.penalty_box.punish(addr)
        from tpumr.core import tracing
        # penalty-box entries on the trace: where a reduce's wall-clock
        # goes while a source recovers (or its map re-executes)
        tracing.instant("shuffle:penalty", map_index=map_index, addr=addr,
                        delay_s=round(delay, 4), failures=per_src)
        if self.reporter is not None:
            self.reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                       TaskCounter.REDUCE_FETCH_FAILURES, 1)
        if per_src % self.retries_per_source == 0:
            # this location has had its chances: report up (the master
            # counts distinct reducers per map attempt and re-executes
            # at mapred.max.fetch.failures.per.map) and drop the cached
            # location so the next round re-resolves from refreshed
            # completion events — a re-run map's new address is picked
            # up WITHOUT restarting the copy phase
            attempt = self._source_hook("attempt_of", map_index, "") or ""
            try:
                self.on_fetch_failure(map_index, attempt)
                tracing.instant("shuffle:fetch_failure_report",
                                map_index=map_index, map_attempt=attempt)
                with self._stats_lock:
                    self.fetch_failures_reported += 1
            except Exception:  # noqa: BLE001 — reporting is best-effort;
                pass           # the penalty/retry loop keeps the reduce alive
            self._source_hook("invalidate", map_index)
        return delay

    # ------------------------------------------------------------ the phase

    def copy_all(self) -> "list[Segment]":
        os.makedirs(self.spill_dir, exist_ok=True)
        results: "list[Segment | None]" = [None] * self.num_maps
        errors: "list[Exception]" = []
        # (ready_at, -advertised_bytes, map_index): failed maps re-enter
        # with a hold-off instead of failing the reduce — the queue is
        # drained only when every map has actually been copied. A
        # PriorityQueue so that among equally-ready entries the largest
        # advertised map output pops first (size-aware shuffle); with
        # size priority off the middle element is constant-0 and the
        # orders degenerate to the legacy readiness-stamp FIFO.
        work: "queue.PriorityQueue[tuple[float, int, int]]" = \
            queue.PriorityQueue()

        def push(ready: float, m: int) -> None:
            size = (self._source_hook("size_of", m, 0) or 0
                    if self.size_priority else 0)
            work.put((ready, -int(size), m))

        for m in range(self.num_maps):
            push(0.0, m)
        outstanding = [self.num_maps]
        lock = threading.Lock()

        def worker() -> None:
            # adopt the task thread's trace context so fetch/penalty
            # spans land under the reduce's run span
            from tpumr.core import tracing
            with tracing.activate_captured(self._trace_ctx):
                worker_body()

        def land(m: int, seg: Segment) -> None:
            self._note_success(m)
            if self.merger is not None and isinstance(seg, MemorySegment):
                # the merger owns it now; results[m] keeps a handle
                # for the error-path sweep (double close is safe)
                self.merger.offer(m, seg)
            elif self.merger is not None and isinstance(seg, DiskSegment):
                # likewise: accumulated spills background-merge into
                # sorted runs while other fetchers wait on the wire
                self.merger.offer_disk(m, seg)
            with lock:
                results[m] = seg
                outstanding[0] -= 1
                completed = self.num_maps - outstanding[0]
            if self.reporter is not None:
                self.reporter.incr_counter(
                    TaskCounter.FRAMEWORK_GROUP,
                    TaskCounter.REDUCE_SHUFFLE_BYTES, seg.raw_length)
                if seg.wire_length:
                    self.reporter.incr_counter(
                        TaskCounter.FRAMEWORK_GROUP,
                        TaskCounter.REDUCE_SHUFFLE_WIRE_BYTES,
                        seg.wire_length)
                self.reporter.incr_counter(
                    TaskCounter.FRAMEWORK_GROUP,
                    TaskCounter.REDUCE_SHUFFLE_SEGMENTS_DISK
                    if isinstance(seg, DiskSegment)
                    else TaskCounter.REDUCE_SHUFFLE_SEGMENTS_MEM, 1)
                self.reporter.progress(completed / self.num_maps)

        def fail(m: int, e: Exception) -> bool:
            """Account one failed round; False when terminal (stop the
            worker), True when the map re-entered the queue."""
            if self._note_failure(m) is None:
                with lock:
                    errors.append(e)
                return False
            # ready now; the pop-side penalty check supplies the
            # (possibly already-cleared) hold-off
            push(time.monotonic(), m)
            return True

        def worker_body() -> None:
            while True:
                with lock:
                    if errors or outstanding[0] <= 0:
                        return
                if self.reporter is not None and self.reporter.aborted():
                    return
                try:
                    item = work.get(timeout=0.05)
                    ready, m = item[0], item[-1]
                except queue.Empty:
                    continue   # others may still re-queue penalized maps
                # the penalty hold is consulted FRESH on every pop (never
                # baked into the stored timestamp): a success against the
                # same address clears the box and the map retries
                # immediately instead of waiting out a stale hold-off
                hold = max(ready, self._penalized_until(m))
                now = time.monotonic()
                if hold > now:
                    # not yet — rotate it to the back and nap briefly so
                    # an all-penalized queue doesn't busy-spin. Waiting
                    # out a penalty is liveness, not a hang: tick the
                    # reaper's keepalive. Re-stamped with NOW so the
                    # priority order can't keep popping one big
                    # penalized map ahead of smaller ready ones (the
                    # penalty itself is still consulted fresh per pop,
                    # never baked into the stamp).
                    if self.reporter is not None:
                        self.reporter.keepalive()
                    push(now, m)
                    time.sleep(min(hold - now, 0.05))
                    continue
                members = self._coalesce(work, m)
                if len(members) > 1:
                    # batched round: one RPC pulls every coalesced
                    # member from the shared source
                    for mm, seg, exc in self._copy_batch(members):
                        if seg is not None:
                            land(mm, seg)
                        elif exc is not None:
                            if not fail(mm, exc):
                                return
                        else:
                            # omitted under the server's byte budget —
                            # not a failure, just didn't fit this frame
                            push(0.0, mm)
                    continue
                try:
                    # with a fetch-failure callback the penalty box IS
                    # the retry loop (one fetch per round); without one,
                    # keep the legacy in-line quick retries + raise
                    seg = (self._copy_one(m)
                           if self.on_fetch_failure is not None
                           else self._copy_with_retries(m))
                except Exception as e:  # noqa: BLE001
                    if not fail(m, e):
                        return
                    continue
                land(m, seg)

        n = min(self.parallel, max(1, self.num_maps))
        threads = [threading.Thread(target=worker,
                                    name=f"shuffle-copier-{i}", daemon=True)
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        aborted = self.reporter is not None and self.reporter.aborted()
        if errors or aborted:
            if self.merger is not None:
                self.merger.abort()
            for seg in results:
                if seg is not None:
                    seg.close()
            if errors:
                raise errors[0]
            self.reporter.raise_if_aborted()
        out: "list[Segment]" = [seg for seg in results if seg is not None]
        if self.merger is not None:
            try:
                runs = self.merger.finish()
            except Exception:
                for seg in out:
                    seg.close()
                raise
            merged = self.merger.merged_ids
            # pre-merged sorted runs first, then live segments in map
            # order — every stream is sorted; the final merge interleaves
            out = list(runs) + [s for s in out if id(s) not in merged]
        return out

    @property
    def inmem_merges(self) -> int:
        """Background in-memory merges performed this copy phase."""
        return 0 if self.merger is None else self.merger.inmem_merges

    @property
    def disk_merges(self) -> int:
        """Background disk-run merges performed this copy phase."""
        return 0 if self.merger is None else self.merger.disk_merges


class RemoteChunkSource:
    """ChunkFetch over tracker RPC (the client half of the chunked
    MapOutputServlet): resolves each map's serving tracker via the
    completion-event locator, then pulls ``get_map_output_chunk``
    ranges. Shared by the in-tracker reduce path and the isolated child
    (which locates through the umbilical event proxy)."""

    def __init__(self, conf: Any, job_id: str,
                 locate: Callable[[int], Any]) -> None:
        self.job_id = job_id
        self.locate = locate
        # clamped to the server's 4 MiB MAX_CHUNK: chunk length is then
        # DETERMINISTIC (min(chunk_bytes, remaining)), which is what
        # lets fetch_chunks predict offsets and pipeline requests
        self.chunk_bytes = min(4 << 20, max(64 * 1024, confkeys.get_int(
            conf, "tpumr.shuffle.chunk.bytes")))
        #: chunk requests kept in flight per leased connection (RTT hiding)
        self.pipeline_depth = max(1, confkeys.get_int(
            conf, "tpumr.shuffle.fetch.pipeline.depth"))
        #: batched multi-segment fetch shape; segments=1 disables batching
        self.batch_segments = max(1, confkeys.get_int(
            conf, "tpumr.shuffle.batch.segments"))
        self.batch_bytes = max(self.chunk_bytes, confkeys.get_int(
            conf, "tpumr.shuffle.batch.bytes"))
        from tpumr.io.compress import wire_codec_or_none
        #: wire codec THIS process can decode natively, else "none" —
        #: never request frames the pure-python fallback can't decompress
        self.wire_codec = wire_codec_or_none(
            confkeys.get(conf, "tpumr.shuffle.wire.codec"))
        #: fetch-failure report seam, wired by the tracker / child so the
        #: ShuffleCopier can report a dead location up the umbilical
        self.on_fetch_failure: "Callable[[int, str], None] | None" = None

    def _decode(self, out: dict) -> dict:
        """Account wire bytes and undo wire compression in place: after
        this, ``len(out['data'])`` is back in payload space, so chunk
        offsets keep composing."""
        data = out.get("data", b"")
        out["wire_len"] = len(data)
        if out.get("wire"):
            from tpumr.io.compress import get_codec
            out["data"] = get_codec(out["wire"]).decompress(data)
        return out

    def __call__(self, map_index: int, partition: int, offset: int) -> dict:
        return self._decode(self.locate(map_index).call(
            "get_map_output_chunk", self.job_id, map_index, partition,
            offset, self.chunk_bytes, self.wire_codec))

    def fetch_chunks(self, map_index: int, partition: int,
                     start: int = 0,
                     total: "int | None" = None) -> "Iterator[dict]":
        """Pipelined chunk stream for one segment: resolve the serving
        address ONCE, lease one pooled connection, keep
        ``pipeline_depth`` chunk requests in flight (``call_begin`` /
        ``call_finish`` — responses collect strictly FIFO), yield
        decoded chunks in order. Offsets are predicted client-side from
        ``total`` because the server's chunk length is deterministic.
        On a transport error the lease is returned dead (a connection
        with uncollected responses is never reused)."""
        proxy = self.locate(map_index)
        lease = getattr(proxy, "lease", None)
        if lease is None:
            # legacy locator (bare RpcClient): sequential chunks
            got = start
            while total is None or got < total:
                out = self(map_index, partition, got)
                total = int(out["total"])
                yield out
                got += len(out["data"])
                if not out["data"] and got < total:
                    raise EOFError(f"empty chunk at {got}/{total} for "
                                   f"map {map_index}")
            return
        cli = lease()
        dead = False
        try:
            if total is None:
                # eager first chunk: learn total before opening the window
                out = self._decode(cli.call(
                    "get_map_output_chunk", self.job_id, map_index,
                    partition, start, self.chunk_bytes, self.wire_codec))
                total = int(out["total"])
                yield out
                start += len(out["data"])
            offsets = range(start, total, self.chunk_bytes)
            inflight = 0
            i = 0
            while inflight or i < len(offsets):
                while i < len(offsets) and inflight < self.pipeline_depth:
                    cli.call_begin(
                        "get_map_output_chunk", self.job_id, map_index,
                        partition, offsets[i], self.chunk_bytes,
                        self.wire_codec)
                    i += 1
                    inflight += 1
                yield self._decode(cli.call_finish())
                inflight -= 1
        except (ConnectionError, OSError):
            dead = True
            raise
        finally:
            # an abandoned window (consumer stopped early, or an error
            # response mid-pipeline) leaves outstanding > 0 — the pool
            # closes such connections instead of reusing them
            proxy.release(cli, dead=dead)

    def fetch_batch(self, map_indexes: "list[int]",
                    partition: int) -> "list[dict]":
        """Many small segments of one source in ONE response frame (the
        wire-level batcher's RPC). Entries come back decoded; a
        per-member lookup failure rides back as an ``error`` entry and
        a byte-budget overflow simply omits trailing members."""
        if not map_indexes:
            return []
        proxy = self.locate(map_indexes[0])
        entries = proxy.call(
            "get_map_outputs_batch", self.job_id, partition,
            list(map_indexes), self.chunk_bytes, self.batch_bytes,
            self.wire_codec)
        for ent in entries:
            if "data" in ent:
                self._decode(ent)
        return entries

    # --- lost-output recovery hooks (delegated to the locator when it
    # --- has them — tasktracker.make_map_locator's MapLocator does)

    def addr_of(self, map_index: int) -> str:
        fn = getattr(self.locate, "addr_of", None)
        return fn(map_index) if fn is not None else ""

    def attempt_of(self, map_index: int) -> str:
        fn = getattr(self.locate, "attempt_of", None)
        return fn(map_index) if fn is not None else ""

    def size_of(self, map_index: int) -> int:
        """Advertised output bytes from the cached completion event
        (0 = unknown) — the copier's largest-first ordering key."""
        fn = getattr(self.locate, "size_of", None)
        return int(fn(map_index) or 0) if fn is not None else 0

    def invalidate(self, map_index: int) -> None:
        """Drop the cached location so the next fetch re-resolves from
        refreshed completion events (a re-run map's new address)."""
        fn = getattr(self.locate, "invalidate", None)
        if fn is not None:
            fn(map_index)
