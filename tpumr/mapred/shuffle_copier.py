"""The shuffle copy phase: parallel, chunk-streamed, RAM-budgeted.

≈ ``ReduceCopier`` inside ``org.apache.hadoop.mapred.ReduceTask`` (reference:
src/mapred/org/apache/hadoop/mapred/ReduceTask.java — MapOutputCopier fetch
threads :659, ShuffleRamManager byte budget with in-memory vs on-disk
shuffle :1080) and the chunk-serving half of the MapOutputServlet
(TaskTracker.java:4050). Re-designed for this runtime:

- ``tpumr.shuffle.parallel.copies`` fetcher threads pull map outputs
  concurrently (the reference's mapred.reduce.parallel.copies);
- segments move as bounded CHUNKS over tracker RPC (``tpumr.shuffle.
  chunk.bytes``) — neither the serving tracker nor the copier ever holds
  an unbounded payload for one request;
- a :class:`ShuffleRamManager` budget decides in-memory vs on-disk per
  segment by its RAW (decompressed) size: small segments decompress into
  the budget, oversized or budget-starved ones stream to local disk and
  are re-read incrementally at merge time (ifile.iter_chunked_segment),
  so reduce-side memory is bounded by budget + copies × chunk.

Divergence from the reference, documented: the reference BLOCKS a fetcher
waiting for budget because concurrent in-memory merge threads free it; here
nothing frees budget mid-copy (segments are consumed by the merge after the
copy phase), so a fetcher that cannot reserve now goes to disk immediately —
same memory bound, no deadlock, one less moving part.
"""

from __future__ import annotations

import os
import queue
import tempfile
import threading
import time
from typing import Any, Callable, Iterator

from tpumr.core.counters import TaskCounter
from tpumr.io import ifile

#: source protocol: fetch_chunk(map_index, partition, offset) -> dict with
#: "data" (payload bytes from offset), "total" (payload length), "raw"
#: (decompressed segment length), "codec".
ChunkFetch = Callable[[int, int, int], dict]


class ShuffleRamManager:
    """In-memory shuffle byte budget (≈ ReduceTask.java:1080). Accounting
    is in RAW segment bytes — what actually sits in memory after
    decompression. ``max_single`` mirrors the reference's rule that one
    segment may claim at most a fraction of the whole budget."""

    def __init__(self, budget_bytes: int,
                 max_single_frac: float = 0.25) -> None:
        self.budget = max(0, int(budget_bytes))
        self.max_single = int(self.budget * max_single_frac)
        self._used = 0
        self._lock = threading.Lock()

    @property
    def used(self) -> int:
        return self._used

    def try_reserve(self, nbytes: int) -> bool:
        """Claim budget for one segment, or refuse (caller spills to
        disk). Never blocks — see the module docstring divergence note."""
        if nbytes > self.max_single:
            return False
        with self._lock:
            if self._used + nbytes > self.budget:
                return False
            self._used += nbytes
            return True

    def release(self, nbytes: int) -> None:
        with self._lock:
            self._used = max(0, self._used - nbytes)


class Segment:
    """One map output's partition segment, iterable as (kbytes, vbytes)."""

    #: raw (decompressed) size, for accounting/diagnostics
    raw_length = 0
    in_memory = False

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemorySegment(Segment):
    """Decompressed segment held under a ShuffleRamManager reservation.
    ``reserved`` is the amount actually claimed from the manager (the
    index-reported raw size) — released EXACTLY, so a writer/index skew
    between reported and actual decompressed size can never drift the
    budget accounting."""

    in_memory = True

    def __init__(self, raw: bytes, ram: ShuffleRamManager | None,
                 reserved: int | None = None) -> None:
        self._raw: bytes | None = raw
        self.raw_length = len(raw)
        self._reserved = self.raw_length if reserved is None else reserved
        self._ram = ram

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        if self._raw is None:
            raise ValueError("segment closed")
        return ifile.iter_segment(self._raw)

    def close(self) -> None:
        if self._raw is not None and self._ram is not None:
            self._ram.release(self._reserved)
        self._raw = None


class DiskSegment(Segment):
    """Compressed payload spilled to a local file; records stream out
    through the incremental decompressor at merge time."""

    def __init__(self, path: str, codec: str, raw_length: int,
                 offset: int = 0, length: int | None = None,
                 owns_file: bool = True) -> None:
        self.path = path
        self.codec = codec
        self.raw_length = raw_length
        self.offset = offset
        self.length = (length if length is not None
                       else os.path.getsize(path) - offset)
        self._owns = owns_file

    def __iter__(self) -> Iterator[tuple[bytes, bytes]]:
        return ifile.iter_chunked_segment(
            ifile.file_region_chunks(self.path, self.offset, self.length),
            self.codec)

    def close(self) -> None:
        if self._owns:
            try:
                os.unlink(self.path)
            except OSError:
                pass


def spill_region_segment(path: str, index: dict,
                         partition: int) -> DiskSegment:
    """A segment view straight over an existing local spill file (the
    LocalJobRunner / same-host path): zero copy, streamed at merge time.
    The spill file is owned by the map side — never deleted here."""
    off, raw_len, part_len = index["partitions"][partition]
    # skip the 4-byte length prefix; the payload is part_len - 4 bytes
    return DiskSegment(path, index.get("codec", "none"), raw_len,
                       offset=off + 4, length=part_len - 4,
                       owns_file=False)


class LocalSegmentSource:
    """Segment source over same-process map outputs (LocalJobRunner):
    replaces the old list-materializing local_fetch_factory — Weak #6's
    unbounded reduce-side memory goes away because nothing is loaded
    until the merge streams it."""

    def __init__(self, map_outputs: "list[tuple[str, dict]]") -> None:
        self._outputs = map_outputs

    def segments(self, partition: int) -> "list[Segment]":
        out: list[Segment] = []
        for path, index in self._outputs:
            if not path:
                continue
            out.append(spill_region_segment(path, index, partition))
        return out


class ShuffleCopier:
    """Run the copy phase: ``copy_all()`` returns every map's segment for
    this reduce's partition, fetched by a pool of copier threads."""

    def __init__(self, conf: Any, source: ChunkFetch, num_maps: int,
                 partition: int, spill_dir: str,
                 reporter: Any = None) -> None:
        self.conf = conf
        self.source = source
        self.num_maps = num_maps
        self.partition = partition
        self.spill_dir = spill_dir
        self.reporter = reporter
        self.parallel = max(1, conf.get_int("tpumr.shuffle.parallel.copies",
                                            5))
        ram_mb = conf.get_float("tpumr.shuffle.ram.mb", 128.0)
        pct = conf.get_float("mapred.job.shuffle.input.buffer.percent", 0.70)
        self.ram = ShuffleRamManager(int(ram_mb * 1024 * 1024 * pct))
        self.retries = conf.get_int("tpumr.shuffle.copy.retries", 3)
        self.backoff_s = conf.get_float("tpumr.shuffle.copy.backoff.ms",
                                        200.0) / 1000.0
        #: observability: how many segments went to disk vs memory
        #: (mutated by parallel workers — guarded by _stats_lock)
        self.spilled_to_disk = 0
        self.copied_in_memory = 0
        self._stats_lock = threading.Lock()

    # ------------------------------------------------------------ one map

    def _copy_one(self, map_index: int) -> Segment:
        first = self.source(map_index, self.partition, 0)
        total = int(first["total"])
        raw = int(first.get("raw", total))
        codec = first.get("codec", "none")
        parts = [first["data"]]
        got = len(first["data"])

        if self.ram.try_reserve(raw):
            # in-memory: pull remaining chunks, decompress into the budget
            try:
                while got < total:
                    nxt = self.source(map_index, self.partition, got)
                    if not nxt["data"]:
                        raise EOFError(
                            f"shuffle source returned empty chunk at "
                            f"{got}/{total} for map {map_index}")
                    parts.append(nxt["data"])
                    got += len(nxt["data"])
                from tpumr.io.compress import get_codec
                raw_bytes = get_codec(codec).decompress(b"".join(parts))
                with self._stats_lock:
                    self.copied_in_memory += 1
                return MemorySegment(raw_bytes, self.ram, reserved=raw)
            except BaseException:
                self.ram.release(raw)
                raise
        # on-disk: stream chunks straight to a local spill file
        fd, path = tempfile.mkstemp(prefix=f"shuffle-m{map_index}-",
                                    suffix=".seg", dir=self.spill_dir)
        try:
            with os.fdopen(fd, "wb") as f:
                for p in parts:
                    f.write(p)
                while got < total:
                    nxt = self.source(map_index, self.partition, got)
                    if not nxt["data"]:
                        raise EOFError(
                            f"shuffle source returned empty chunk at "
                            f"{got}/{total} for map {map_index}")
                    f.write(nxt["data"])
                    got += len(nxt["data"])
        except BaseException:
            try:
                os.unlink(path)
            except OSError:
                pass
            raise
        with self._stats_lock:
            self.spilled_to_disk += 1
        return DiskSegment(path, codec, raw)

    def _copy_with_retries(self, map_index: int) -> Segment:
        last: Exception | None = None
        for attempt in range(self.retries + 1):
            try:
                return self._copy_one(map_index)
            except Exception as e:  # noqa: BLE001 — fetch failure is data
                last = e
                if attempt < self.retries:
                    time.sleep(self.backoff_s * (2 ** attempt))
        raise RuntimeError(
            f"shuffle fetch of map {map_index} partition {self.partition} "
            f"failed after {self.retries + 1} attempts: {last}") from last

    # ------------------------------------------------------------ the phase

    def copy_all(self) -> "list[Segment]":
        os.makedirs(self.spill_dir, exist_ok=True)
        results: "list[Segment | None]" = [None] * self.num_maps
        errors: "list[Exception]" = []
        work: "queue.Queue[int]" = queue.Queue()
        for m in range(self.num_maps):
            work.put(m)
        done = [0]
        lock = threading.Lock()

        def worker() -> None:
            while True:
                with lock:
                    if errors:
                        return
                if self.reporter is not None and self.reporter.aborted():
                    return
                try:
                    m = work.get_nowait()
                except queue.Empty:
                    return
                try:
                    seg = self._copy_with_retries(m)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(e)
                    return
                with lock:
                    results[m] = seg
                    done[0] += 1
                if self.reporter is not None:
                    self.reporter.incr_counter(
                        TaskCounter.FRAMEWORK_GROUP,
                        TaskCounter.REDUCE_SHUFFLE_BYTES, seg.raw_length)
                    self.reporter.incr_counter(
                        TaskCounter.FRAMEWORK_GROUP,
                        TaskCounter.REDUCE_SHUFFLE_SEGMENTS_DISK
                        if isinstance(seg, DiskSegment)
                        else TaskCounter.REDUCE_SHUFFLE_SEGMENTS_MEM, 1)
                    self.reporter.progress(done[0] / self.num_maps)

        n = min(self.parallel, max(1, self.num_maps))
        threads = [threading.Thread(target=worker,
                                    name=f"shuffle-copier-{i}", daemon=True)
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        aborted = self.reporter is not None and self.reporter.aborted()
        if errors or aborted:
            for seg in results:
                if seg is not None:
                    seg.close()
            if errors:
                raise errors[0]
            self.reporter.raise_if_aborted()
        return [seg for seg in results if seg is not None]


class RemoteChunkSource:
    """ChunkFetch over tracker RPC (the client half of the chunked
    MapOutputServlet): resolves each map's serving tracker via the
    completion-event locator, then pulls ``get_map_output_chunk``
    ranges. Shared by the in-tracker reduce path and the isolated child
    (which locates through the umbilical event proxy)."""

    def __init__(self, conf: Any, job_id: str,
                 locate: Callable[[int], Any]) -> None:
        self.job_id = job_id
        self.locate = locate
        self.chunk_bytes = max(64 * 1024,
                               conf.get_int("tpumr.shuffle.chunk.bytes",
                                            1 << 20))

    def __call__(self, map_index: int, partition: int, offset: int) -> dict:
        return self.locate(map_index).call(
            "get_map_output_chunk", self.job_id, map_index, partition,
            offset, self.chunk_bytes)
