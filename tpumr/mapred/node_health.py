"""Node health checking + task memory management.

≈ the reference's TaskTracker self-checks (SURVEY.md §5):
``NodeHealthCheckerService`` (367 LoC — runs an operator-supplied script;
any output starting with ERROR marks the node unhealthy and the
JobTracker stops assigning to it) and ``TaskMemoryManagerThread`` (kills
tasks whose process tree exceeds the configured memory limit).

The memory manager watches *subprocess* tasks (pipes/streaming children)
via /proc RSS — in-process kernel tasks live inside the runner and are
bounded by the runner process itself (documented divergence: the
reference's every task is a child JVM).
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
from typing import Any, Callable


class NodeHealthChecker:
    """≈ NodeHealthCheckerService: periodic external script."""

    def __init__(self, script: str, interval_s: float = 10.0,
                 timeout_s: float = 30.0) -> None:
        self.script = script
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.healthy = True
        self.report = ""
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def check_once(self) -> None:
        try:
            proc = subprocess.run(
                ["/bin/sh", "-c", self.script], capture_output=True,
                text=True, timeout=self.timeout_s)
            out = (proc.stdout or "").strip()
            # reference contract: a line starting with ERROR == unhealthy;
            # nonzero exit alone is NOT unhealthy (script bugs must not
            # depool nodes — NodeHealthCheckerService semantics)
            bad = [l for l in out.splitlines() if l.startswith("ERROR")]
            self.healthy = not bad
            self.report = "; ".join(bad)
        except subprocess.TimeoutExpired:
            self.healthy = False
            self.report = "health script timed out"
        except Exception as e:  # noqa: BLE001
            self.healthy = True  # can't run the script ≠ unhealthy node
            self.report = f"health script error: {e}"

    def start(self) -> "NodeHealthChecker":
        if self._thread is None:
            self.check_once()
            self._thread = threading.Thread(target=self._loop,
                                            name="node-health", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.check_once()


def process_rss_bytes(pid: int) -> int | None:
    """VmRSS of one process from /proc (Linux)."""
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        return None
    return None


class TaskMemoryManager:
    """≈ TaskMemoryManagerThread: sample registered task subprocesses,
    kill those above their limit (the kill callback owns process-tree
    semantics)."""

    def __init__(self, interval_s: float = 1.0) -> None:
        self.interval_s = interval_s
        self._lock = threading.Lock()
        #: attempt_id -> (pid, limit_bytes, kill_cb)
        self._tasks: dict[str, tuple[int, int, Callable[[str], None]]] = {}
        self.killed: list[str] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def register(self, attempt_id: str, pid: int, limit_bytes: int,
                 kill_cb: Callable[[str], None]) -> None:
        with self._lock:
            self._tasks[attempt_id] = (pid, limit_bytes, kill_cb)
        # self-starting: a limit set only in the JOB conf must still be
        # enforced even when the tracker conf never started the sampler
        self.start()

    def unregister(self, attempt_id: str) -> None:
        with self._lock:
            self._tasks.pop(attempt_id, None)

    def check_once(self) -> list[str]:
        with self._lock:
            tasks = list(self._tasks.items())
        over = []
        for aid, (pid, limit, kill_cb) in tasks:
            rss = process_rss_bytes(pid)
            if rss is not None and limit > 0 and rss > limit:
                over.append(aid)
                self.killed.append(aid)
                try:
                    kill_cb(aid)
                except Exception:  # noqa: BLE001
                    pass
                self.unregister(aid)
        return over

    def start(self) -> "TaskMemoryManager":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="task-memory", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.check_once()


#: process-wide manager — subprocess task runners (pipes/streaming)
#: register their children here; the owning NodeRunner starts/stops it
GLOBAL_MEMORY_MANAGER = TaskMemoryManager()
