"""Node health checking + task memory management.

≈ the reference's TaskTracker self-checks (SURVEY.md §5):
``NodeHealthCheckerService`` (367 LoC — runs an operator-supplied script;
any output starting with ERROR marks the node unhealthy and the
JobTracker stops assigning to it) and ``TaskMemoryManagerThread`` (kills
tasks whose process tree exceeds the configured memory limit).

The memory manager watches *subprocess* tasks (pipes/streaming children)
via /proc RSS — in-process kernel tasks live inside the runner and are
bounded by the runner process itself (documented divergence: the
reference's every task is a child JVM).
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
from typing import Any, Callable


class NodeHealthChecker:
    """≈ NodeHealthCheckerService: periodic external script."""

    def __init__(self, script: str, interval_s: float = 10.0,
                 timeout_s: float = 30.0) -> None:
        self.script = script
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.healthy = True
        self.report = ""
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def check_once(self) -> None:
        try:
            proc = subprocess.run(
                ["/bin/sh", "-c", self.script], capture_output=True,
                text=True, timeout=self.timeout_s)
            out = (proc.stdout or "").strip()
            # reference contract: a line starting with ERROR == unhealthy;
            # nonzero exit alone is NOT unhealthy (script bugs must not
            # depool nodes — NodeHealthCheckerService semantics)
            bad = [l for l in out.splitlines() if l.startswith("ERROR")]
            self.healthy = not bad
            self.report = "; ".join(bad)
        except subprocess.TimeoutExpired:
            self.healthy = False
            self.report = "health script timed out"
        except Exception as e:  # noqa: BLE001
            self.healthy = True  # can't run the script ≠ unhealthy node
            self.report = f"health script error: {e}"

    def start(self) -> "NodeHealthChecker":
        if self._thread is None:
            self.check_once()
            self._thread = threading.Thread(target=self._loop,
                                            name="node-health", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.check_once()


def process_rss_bytes(pid: int) -> int | None:
    """VmRSS of one process from /proc (Linux)."""
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        return None
    return None


class TaskMemoryManager:
    """≈ TaskMemoryManagerThread: sample registered task subprocesses,
    kill those above their limit (the kill callback owns process-tree
    semantics)."""

    def __init__(self, interval_s: float = 1.0) -> None:
        self.interval_s = interval_s
        self._lock = threading.Lock()
        #: attempt_id -> (pid, limit_bytes, kill_cb)
        self._tasks: dict[str, tuple[int, int, Callable[[str], None]]] = {}
        self.killed: list[str] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def register(self, attempt_id: str, pid: int, limit_bytes: int,
                 kill_cb: Callable[[str], None]) -> None:
        with self._lock:
            self._tasks[attempt_id] = (pid, limit_bytes, kill_cb)
        # self-starting: a limit set only in the JOB conf must still be
        # enforced even when the tracker conf never started the sampler
        self.start()

    def unregister(self, attempt_id: str) -> None:
        with self._lock:
            self._tasks.pop(attempt_id, None)

    def check_once(self) -> list[str]:
        with self._lock:
            tasks = list(self._tasks.items())
        over = []
        for aid, (pid, limit, kill_cb) in tasks:
            rss = process_rss_bytes(pid)
            if rss is not None and limit > 0 and rss > limit:
                over.append(aid)
                self.killed.append(aid)
                try:
                    kill_cb(aid)
                except Exception:  # noqa: BLE001
                    pass
                self.unregister(aid)
        return over

    def start(self) -> "TaskMemoryManager":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="task-memory", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.check_once()


#: process-wide manager — subprocess task runners (pipes/streaming)
#: register their children here; the owning NodeRunner starts/stops it
GLOBAL_MEMORY_MANAGER = TaskMemoryManager()


def default_tpu_probe(device_id: int) -> None:
    """Trivial device liveness op: put a tiny array on the device and
    force materialization. Raises when the device (or the runtime path
    to it) is sick — exactly the signal the quarantine cares about."""
    import jax
    import numpy as np
    devices = jax.local_devices()
    d = devices[device_id % len(devices)]
    jax.device_put(np.ones(8, np.float32), d).block_until_ready()


class TpuDeviceHealth:
    """Per-device accelerator quarantine (new capability — the reference
    has no device-granular health at all: a sick GPU kept receiving
    tasks until the tracker blacklisted wholesale).

    ``threshold`` CONSECUTIVE device-classed task failures on device *d*
    mark it bad: the tracker stops advertising its slot and the
    scheduler stops deriving free device ids from it. A background probe
    (``probe(device_id)`` — default a trivial jnp op) retries the device
    on a capped exponential backoff and re-admits it on the first
    success, so a transient runtime wedge doesn't depool hardware
    forever. A success between failures resets the consecutive count
    (intermittent flakiness is the penalty box's job, not quarantine's).
    """

    def __init__(self, n_devices: int, threshold: int = 3,
                 probe: "Callable[[int], Any] | None" = None,
                 probe_interval_s: float = 10.0,
                 probe_max_interval_s: float = 300.0) -> None:
        self.n_devices = max(0, n_devices)
        self.threshold = threshold
        self.probe = probe if probe is not None else default_tpu_probe
        self.probe_interval_s = max(0.05, probe_interval_s)
        self.probe_max_interval_s = max(self.probe_interval_s,
                                        probe_max_interval_s)
        self._lock = threading.Lock()
        self._consecutive: dict[int, int] = {}
        #: device -> (next_probe_monotonic, current_backoff_s)
        self._quarantined: dict[int, tuple[float, float]] = {}
        #: total quarantine ENTRIES (monotone counter for /metrics)
        self.quarantine_events = 0
        #: quarantines lifted by a successful probe
        self.restore_events = 0
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------- recording

    def record_failure(self, device_id: int) -> bool:
        """One device-classed task failure on ``device_id``. Returns
        True when this failure newly quarantined the device."""
        if not 0 <= device_id < self.n_devices or self.threshold <= 0:
            return False
        with self._lock:
            if device_id in self._quarantined:
                return False
            n = self._consecutive.get(device_id, 0) + 1
            self._consecutive[device_id] = n
            if n < self.threshold:
                return False
            self._quarantined[device_id] = (
                time.monotonic() + self.probe_interval_s,
                self.probe_interval_s)
            self._consecutive.pop(device_id, None)
            self.quarantine_events += 1
        self._ensure_thread()
        self._wake.set()
        return True

    def record_success(self, device_id: int) -> None:
        """A task completed fine on the device — consecutive-failure
        streak broken."""
        with self._lock:
            self._consecutive.pop(device_id, None)

    # --------------------------------------------------------- queries

    def quarantined(self) -> "list[int]":
        with self._lock:
            return sorted(self._quarantined)

    def is_quarantined(self, device_id: int) -> bool:
        with self._lock:
            return device_id in self._quarantined

    # ----------------------------------------------------------- probe

    def _ensure_thread(self) -> None:
        with self._lock:   # concurrent quarantines must not double-start
            if self._thread is not None:
                return
            self._thread = threading.Thread(target=self._probe_loop,
                                            name="tpu-device-probe",
                                            daemon=True)
        self._thread.start()

    def probe_once(self, now: "float | None" = None) -> "list[int]":
        """Probe every quarantined device whose deadline passed; restore
        the ones whose probe succeeds. Returns restored ids (also the
        deterministic seam the tests drive instead of the thread)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            due = [d for d, (at, _b) in self._quarantined.items()
                   if at <= now]
        restored = []
        for d in due:
            try:
                self.probe(d)
            except Exception:  # noqa: BLE001 — still sick: back off
                with self._lock:
                    if d in self._quarantined:
                        _at, backoff = self._quarantined[d]
                        backoff = min(backoff * 2,
                                      self.probe_max_interval_s)
                        self._quarantined[d] = (now + backoff, backoff)
                continue
            with self._lock:
                if self._quarantined.pop(d, None) is not None:
                    self.restore_events += 1
                    restored.append(d)
        return restored

    def _next_deadline(self) -> "float | None":
        with self._lock:
            if not self._quarantined:
                return None
            return min(at for at, _b in self._quarantined.values())

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            deadline = self._next_deadline()
            if deadline is None:
                self._wake.wait(self.probe_max_interval_s)
                self._wake.clear()
                continue
            delay = max(0.0, deadline - time.monotonic())
            if delay:
                if self._stop.wait(min(delay, 1.0)):
                    return
                continue
            self.probe_once()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
