"""Sharded master runtime: N shard worker processes + thin coordinator.

One Python process tops out folding ~400 trackers' heartbeats at the
250ms dual-p99 SLO — after five PRs of lock work the profiler shows
throughput (rpc dispatch + fold CPU on one core with one GIL), not
locking, as the wall. This module breaks the ceiling the only way a
GIL permits: **partition the fleet across processes**. Each shard is a
complete :class:`~tpumr.mapred.jobtracker.JobMaster` (registry stripe,
delta decode, status fold, try-lock scheduling, completion events,
history, recovery) owning the trackers that hash to it AND the jobs the
coordinator routes to it; the :class:`ShardedMaster` coordinator stays
off every heartbeat and serves only the client surface (submit/status/
kill routing), shard supervision, and the merged metrics/flight-record
view.

Design rules, in order of importance:

* **The coordinator never sits on the heartbeat path.** Trackers talk
  straight to their shard (``tracker_shard(name, n)`` is a pure
  function of the tracker name, computable by any party with the shard
  map). The coordinator's lock (rank ``coordinator``, 18) guards only
  routing tables and shard records; every blocking edge — shard RPC,
  ``Popen``, ``wait`` — runs OUTSIDE it, which ``tpumr lint`` proves.
* **A dead shard is a master restart scoped to its trackers.** The
  monitor respawns it on its PINNED port with recovery on; its
  trackers re-join and their in-flight attempts are adopted by the
  re-submitted jobs — the PR-9 protocol, unchanged. Sibling shards
  never notice.
* **Shards share nothing.** Separate history subdirs, distinct
  cluster-id suffixes (job ids can't collide), no cross-shard RPC.
  A job's splits, attempts, and completion events all live on one
  shard, so the fast path stays exactly as profiled single-process.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
import zlib
from typing import Any

from tpumr.core import confkeys
from tpumr.ipc.rpc import RpcClient, RpcServer
from tpumr.mapred.jobtracker import PROTOCOL_VERSION
from tpumr.metrics.histogram import Histogram, typed_delta


def tracker_shard(name: str, n: int) -> int:
    """Which shard owns tracker ``name``. crc32, NOT ``hash()`` —
    Python string hashing is per-process seed-randomized and the fleet,
    the shards, and the coordinator must all agree."""
    return zlib.crc32(str(name).encode("utf-8")) % max(1, int(n))


def make_master(conf: Any, host: str = "127.0.0.1", port: int = 0):
    """``tpumr.master.shards`` > 0 → a :class:`ShardedMaster`, else the
    classic single-process :class:`JobMaster` — one construction seam
    for the scenario lab, the bench, and the CLI."""
    if confkeys.get_int(conf, "tpumr.master.shards") > 0:
        return ShardedMaster(conf, host=host, port=port)
    from tpumr.mapred.jobtracker import JobMaster
    return JobMaster(conf, host=host, port=port)


class _FleetSize:
    """``len()``-able stand-in for the single master's tracker registry
    (the flight recorder and dashboards only ever take ``len``)."""

    def __init__(self) -> None:
        self.n = 0

    def __len__(self) -> int:
        return self.n


class _Shard:
    """Coordinator-side record of one worker process."""

    __slots__ = ("index", "host", "port", "pid", "proc", "client",
                 "registered", "restarts", "trackers", "cpu_shares",
                 "rpc_inflight_peak", "cluster_id", "gauges")

    def __init__(self, index: int, host: str) -> None:
        self.index = index
        self.host = host
        self.port = 0            # pinned after first registration
        self.pid = 0
        self.proc: Any = None
        self.client: "RpcClient | None" = None
        self.registered = threading.Event()
        self.restarts = 0
        self.trackers = 0
        self.cpu_shares: "dict | None" = None
        self.rpc_inflight_peak = 0
        self.cluster_id = ""
        #: last polled jobtracker gauges (instructed cadence, history
        #: queue backpressure) — point-in-time truths that can't be
        #: summed into the merged registries, so they stay per shard
        self.gauges: dict = {}


class ShardedMaster:
    """Coordinator: spawn/supervise shards, route the client RPC
    surface by job ownership, fold per-shard metrics into one merged
    view. Exposes the :class:`JobMaster` attributes the scale harness,
    scenario lab, and flight recorder consume (``address``, ``metrics``,
    ``trackers``, ``_class_hists``, ``_hb_seconds``/``_hb_lag``,
    ``brownout``, ``scenario_name``) so every consumer treats either
    master shape uniformly."""

    #: how long to wait for a (re)spawned shard to register
    REGISTER_TIMEOUT_S = 30.0

    def __init__(self, conf: Any, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.conf = conf
        self.host = host
        self.n = max(1, confkeys.get_int(conf, "tpumr.master.shards"))
        self.poll_s = confkeys.get_int(
            conf, "tpumr.master.shards.poll.ms") / 1000.0
        from tpumr.metrics import MetricsSystem
        self.metrics = MetricsSystem(
            "jobtracker",
            period_s=confkeys.get_int(conf, "tpumr.metrics.period.ms") / 1000)
        self._mreg = self.metrics.new_registry("jobtracker")
        #: per-source merged registries (shards ship typed snapshots;
        #: counters fold as reset-safe deltas so a respawned shard's
        #: zeros don't regress the totals)
        self._regs = {"jobtracker": self._mreg}
        from tpumr.metrics.locks import RANK_COORDINATOR, InstrumentedRLock
        self._coord_lock = InstrumentedRLock(name="coordinator",
                                       rank=RANK_COORDINATOR)
        self._shards = [_Shard(k, host) for k in range(self.n)]
        #: job id → owning shard index (insert-only, like the job table)
        self._job_shard: "dict[str, int]" = {}
        #: merged old→new recovered-job aliases from every shard respawn
        self._recovered: "dict[str, str]" = {}
        self._rr = 0
        self._stop = threading.Event()
        self._threads: "list[threading.Thread]" = []
        #: thread-confined to the poll loop — previous typed states for
        #: delta folding, keyed (shard, source, kind, name)
        self._prev: "dict[tuple, dict]" = {}

        # ---- JobMaster-compatible merged surface -------------------
        self.trackers = _FleetSize()
        self.brownout = None
        self.scenario_name = str(confkeys.get(
            conf, "tpumr.scenario.name") or "")
        self._hb_seconds = self._mreg.histogram("heartbeat_seconds")
        self._hb_lag = self._mreg.histogram("heartbeat_lag_seconds")
        #: merged per-class latency hists, same shape the single master
        #: keeps — the flight recorder's per-class verdicts read these
        self._class_hists: "dict[tuple[str, str], Histogram]" = {}
        #: per-shard heartbeat hists for the recorder's per-shard
        #: breach windows: (shard index, metric name) → Histogram
        self._shard_hists: "dict[tuple[int, str], Histogram]" = {}

        self._mreg.set_gauge("shards", lambda: self.n)
        self._mreg.set_gauge("shard_trackers_total",
                             lambda: len(self.trackers))

        from tpumr.security import rpc_secret
        self._rpc_secret = rpc_secret(conf)
        # client surface only — no fast methods: every handler here
        # either blocks on a shard RPC or mutates routing tables, and
        # belongs on the handler pool, never inline in a reactor loop
        self._server = RpcServer(self, host=host, port=port,
                                 secret=self._rpc_secret)
        self._server.metrics = self.metrics.new_registry("rpc")
        self._regs["rpc"] = self._server.metrics

        from tpumr.metrics.flightrec import ShardFlightRecorder
        self.flightrec = ShardFlightRecorder.from_conf(conf, self)
        self._http: Any = None
        self._http_port = conf.get_int("mapred.job.tracker.http.port", -1)

    # ------------------------------------------------------------ lifecycle

    @property
    def address(self) -> "tuple[str, int]":
        return self._server.address

    def start(self) -> "ShardedMaster":
        self._server.start()
        for shard in self._shards:
            self._spawn(shard)
        deadline = time.monotonic() + self.REGISTER_TIMEOUT_S
        for shard in self._shards:
            if not shard.registered.wait(
                    max(0.1, deadline - time.monotonic())):
                self.stop()   # don't leak half a fleet of workers
                raise RuntimeError(
                    f"shard {shard.index} failed to register within "
                    f"{self.REGISTER_TIMEOUT_S:.0f}s")
        for target, name in ((self._monitor_loop, "shard-monitor"),
                             (self._poll_loop, "shard-poll")):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        self.metrics.start()
        if self.flightrec is not None:
            self.flightrec.start()
        if self._http_port >= 0:
            self._http = self._build_http(self._http_port).start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.flightrec is not None:
            self.flightrec.stop()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []
        for shard in self._shards:
            proc = shard.proc
            if proc is None:
                continue
            try:
                if proc.stdin:
                    proc.stdin.close()   # EOF = orderly shard shutdown
                proc.wait(timeout=10.0)
            except Exception:  # noqa: BLE001 — escalate to SIGKILL
                proc.kill()
                try:
                    proc.wait(timeout=5.0)
                except Exception:  # noqa: BLE001
                    pass
            if shard.client is not None:
                shard.client.close()
        self.metrics.stop()
        if self._http is not None:
            self._http.stop()
        self._server.stop()

    # ------------------------------------------------------------ spawning

    def _spawn(self, shard: "_Shard") -> None:
        """Launch one worker (never under the coordinator lock: Popen
        forks). ``shard.port`` 0 = first boot on an ephemeral port;
        non-zero = respawn pinned to the address its trackers know."""
        spec = {
            "index": shard.index,
            "host": shard.host,
            "port": shard.port,
            "coordinator": list(self._server.address),
            "conf": self.conf.to_dict(),
        }
        shard.registered.clear()
        proc = subprocess.Popen(
            [sys.executable, "-m", "tpumr.mapred.shard_worker"],
            stdin=subprocess.PIPE,
            stdout=subprocess.DEVNULL,
            stderr=None,            # shard tracebacks surface on ours
            close_fds=True)
        assert proc.stdin is not None
        proc.stdin.write((json.dumps(spec, default=str) + "\n").encode())
        proc.stdin.flush()          # stdin stays OPEN: EOF = parent died
        with self._coord_lock:
            shard.proc = proc

    def register_shard(self, index: int, host: str, port: int,
                       pid: int) -> dict:
        """Called by each worker once its JobMaster is serving. On a
        RESPAWN registration the coordinator also pulls the shard's
        recovered-job aliases so client polls on pre-kill job ids route
        to the resubmitted jobs — the restart rebinding surface, merged
        across shards."""
        shard = self._shards[int(index)]
        client = RpcClient(str(host), int(port), secret=self._rpc_secret)
        respawn = shard.restarts > 0
        with self._coord_lock:
            old = shard.client
            shard.host, shard.port, shard.pid = str(host), int(port), int(pid)
            shard.client = client
        if old is not None:
            old.close()
        if respawn:
            self._pull_recovered(shard)
        shard.registered.set()
        return {"index": int(index), "shards": self.n}

    def _pull_recovered(self, shard: "_Shard") -> None:
        """Merge one shard's old→new recovered-job map into the
        coordinator's alias table and ownership routing."""
        try:
            recovered = shard.client.call("get_recovered_jobs")
        except Exception:  # noqa: BLE001 — poll loop retries routing
            return
        with self._coord_lock:
            for old_id, new_id in (recovered or {}).items():
                self._recovered[old_id] = new_id
                self._job_shard[new_id] = shard.index
                self._job_shard.setdefault(old_id, shard.index)

    def _monitor_loop(self) -> None:
        """Reap dead shard processes and respawn them on their pinned
        ports. A kill -9'd shard comes back with recovery on; its
        trackers re-join within one heartbeat interval and the adoption
        protocol takes it from there."""
        while not self._stop.wait(0.1):
            for shard in self._shards:
                proc = shard.proc
                if proc is None or proc.poll() is None:
                    continue
                if self._stop.is_set():
                    return
                shard.restarts += 1
                self._mreg.incr("shard_restarts")
                self._mreg.incr(f"shard_restarts|shard={shard.index}")
                self._spawn(shard)
                shard.registered.wait(self.REGISTER_TIMEOUT_S)

    # ------------------------------------------------------------ folding

    def _poll_loop(self) -> None:
        """Pull every shard's typed snapshot on a period and fold it
        into the merged view. Histograms and counters arrive CUMULATIVE
        per shard process generation; folding deltas (reset-safe on
        count shrink) makes a respawn look like a flat spot, not a
        regression. ``_prev`` is confined to this thread — the fold
        needs no coordinator lock at all."""
        while not self._stop.wait(self.poll_s):
            total_trackers = 0
            for shard in self._shards:
                client = shard.client
                if client is None or not shard.registered.is_set():
                    continue
                try:
                    snap = client.call("shard_snapshot")
                except Exception:  # noqa: BLE001 — dead shard; monitor acts
                    continue
                self._fold_shard(shard, snap)
                total_trackers += shard.trackers
            self.trackers.n = total_trackers

    def _fold_shard(self, shard: "_Shard", snap: dict) -> None:
        k = shard.index
        shard.trackers = int(snap.get("trackers") or 0)
        shard.cpu_shares = snap.get("cpu_shares")
        shard.rpc_inflight_peak = int(snap.get("rpc_inflight_peak") or 0)
        shard.cluster_id = str(snap.get("cluster_id") or "")
        for source, typed in (snap.get("metrics") or {}).items():
            if source == "jobtracker":
                shard.gauges = dict(typed.get("gauges") or {})
            reg = self._regs.get(source)
            if reg is None:
                reg = self._regs[source] = self.metrics.new_registry(source)
            for name, val in (typed.get("counters") or {}).items():
                key = (k, source, "c", name)
                base = self._prev.get(key, 0)
                try:
                    inc = val - base if val >= base else val
                except TypeError:
                    continue
                self._prev[key] = val  # type: ignore[assignment]
                if inc:
                    reg.incr(name, inc)
            for name, cur in (typed.get("histograms") or {}).items():
                key = (k, source, "h", name)
                delta = typed_delta(cur, self._prev.get(key))
                self._prev[key] = cur
                if not delta or not delta.get("count"):
                    continue
                reg.histogram(name, delta.get("bounds") or None) \
                    .merge_typed(delta)
                if source == "jobtracker" and name in (
                        "heartbeat_seconds", "heartbeat_lag_seconds"):
                    h = self._shard_hists.get((k, name))
                    if h is None:
                        h = self._shard_hists[(k, name)] = Histogram(
                            f"{name}|shard={k}",
                            delta.get("bounds") or None)
                    h.merge_typed(delta)
        for label, cur in (snap.get("class_hists") or {}).items():
            kind, _, cls = label.partition("|")
            key = (k, "class", "h", label)
            delta = typed_delta(cur, self._prev.get(key))
            self._prev[key] = cur
            if not delta or not delta.get("count"):
                continue
            h = self._class_hists.get((kind, cls))
            if h is None:
                h = self._class_hists[(kind, cls)] = Histogram(
                    f"class_{kind}_seconds|class={cls}",
                    delta.get("bounds") or None)
            h.merge_typed(delta)

    # ------------------------------------------------------------ routing

    def get_protocol_version(self) -> int:
        return PROTOCOL_VERSION

    def shard_map(self) -> "list[tuple[str, int]]":
        """Tracker-facing topology: index → (host, port). Position in
        the list IS the shard index ``tracker_shard`` selects."""
        with self._coord_lock:
            return [(s.host, s.port) for s in self._shards]

    def get_shard_map(self) -> "list[list]":
        return [[h, p] for h, p in self.shard_map()]

    def shard_stats(self) -> dict:
        """Per-shard operational truth for dashboards, the bench's
        per-shard ``cpu_share`` columns, and incident bundles."""
        with self._coord_lock:
            shards = list(self._shards)
        return {
            str(s.index): {
                "address": [s.host, s.port],
                "pid": s.pid,
                "restarts": s.restarts,
                "trackers": s.trackers,
                "cluster_id": s.cluster_id,
                "rpc_inflight_peak": s.rpc_inflight_peak,
                "cpu_shares": s.cpu_shares,
                "interval_instructed_ms": int(s.gauges.get(
                    "heartbeat_interval_instructed_ms", 0) or 0),
                "history_queue_depth": int(s.gauges.get(
                    "history_queue_depth", 0) or 0),
                "history_writes_dropped": int(s.gauges.get(
                    "history_writes_dropped", 0) or 0),
            } for s in shards}

    def _owner(self, job_id: str) -> "int | None":
        with self._coord_lock:
            k = self._job_shard.get(job_id)
            if k is None:
                alias = self._recovered.get(job_id)
                if alias is not None:
                    k = self._job_shard.get(alias)
            return k

    def _call_owner(self, job_id: str, method: str, *args: Any) -> Any:
        """Route a job-scoped client call to its owning shard; unknown
        ids probe every shard (each shard serves its own retired and
        recovered jobs from history) and cache the answer."""
        k = self._owner(job_id)
        if k is not None:
            return self._shards[k].client.call(method, job_id, *args)
        last_err: "Exception | None" = None
        for shard in self._shards:
            client = shard.client
            if client is None:
                continue
            try:
                out = client.call(method, job_id, *args)
            except Exception as e:  # noqa: BLE001 — not this shard's job
                last_err = e
                continue
            with self._coord_lock:
                self._job_shard.setdefault(job_id, shard.index)
            return out
        raise last_err if last_err is not None \
            else RuntimeError(f"unknown job {job_id}")

    def submit_job(self, conf: dict, splits: list) -> str:
        """Round-robin a new job onto a shard; the job's whole life
        (splits, attempts, events, history) stays there. Falls over to
        the next shard if the chosen one is mid-respawn — submission
        availability degrades, never the whole surface."""
        last_err: "Exception | None" = None
        for _ in range(self.n):
            with self._coord_lock:
                k = self._rr % self.n
                self._rr += 1
            client = self._shards[k].client
            if client is None:
                continue
            try:
                job_id = client.call("submit_job", conf, splits)
            except Exception as e:  # noqa: BLE001 — try next shard
                last_err = e
                continue
            with self._coord_lock:
                self._job_shard[str(job_id)] = k
            self._mreg.incr("jobs_routed")
            self._mreg.incr(f"jobs_routed|shard={k}")
            return job_id
        raise last_err if last_err is not None \
            else RuntimeError("no shard accepted the job")

    def get_job_status(self, job_id: str) -> dict:
        return self._call_owner(str(job_id), "get_job_status")

    def get_counters(self, job_id: str) -> dict:
        return self._call_owner(str(job_id), "get_counters")

    def get_task_reports(self, job_id: str, kind: str = "map") -> list:
        return self._call_owner(str(job_id), "get_task_reports", kind)

    def kill_job(self, job_id: str, user: str = "") -> Any:
        return self._call_owner(str(job_id), "kill_job", user)

    def get_recovered_jobs(self) -> dict:
        with self._coord_lock:
            return dict(self._recovered)

    # ------------------------------------------------------------ chaos

    def kill_shard(self, index: int) -> dict:
        """SIGKILL one shard worker (the scenario engine's shard_kill
        chaos and the failover tests call this in-process). The monitor
        notices within ~100ms and respawns it on the pinned port."""
        shard = self._shards[int(index)]
        proc, pid = shard.proc, shard.pid
        shard.registered.clear()
        if proc is not None:
            proc.kill()
        self._mreg.incr("shards_killed")
        return {"index": int(index), "pid": pid}

    def wait_shard_ready(self, index: int,
                         timeout_s: float = 30.0) -> bool:
        """Block until shard ``index`` is registered and serving
        (test/chaos convenience — NOT part of the client surface)."""
        return self._shards[int(index)].registered.wait(timeout_s)

    # ------------------------------------------------------------ http

    def _build_http(self, port: int):
        """Merged operator surface: /cluster over all shards, per-shard
        stats, and the uniform /metrics + /metrics/prom exposition fed
        by the folded registries."""
        from tpumr.http import StatusHttpServer
        srv = StatusHttpServer("coordinator", port=port)

        def cluster_info(q: dict) -> dict:
            with self._coord_lock:
                jobs = len(self._job_shard)
            return {
                "shards": self.n,
                "trackers": len(self.trackers),
                "jobs_routed": jobs,
                "shard_map": self.get_shard_map(),
            }

        srv.add_json("cluster", cluster_info)
        srv.add_json("shards", lambda q: self.shard_stats())
        srv.attach_metrics(self.metrics)
        srv.add_page("index", lambda q: (
            f"<h1>Coordinator — {self.n} shards, "
            f"{len(self.trackers)} trackers</h1>"))
        return srv
