"""Job/task/attempt identifiers.

≈ ``org.apache.hadoop.mapred.{JobID,TaskID,TaskAttemptID}`` (reference:
src/mapred/org/apache/hadoop/mapred/JobID.java etc.) with the same string
shapes: ``job_<cluster>_<n>``, ``task_<cluster>_<n>_[mr]_<t>``,
``attempt_<cluster>_<n>_[mr]_<t>_<a>``.

``__str__`` is memoized on each (frozen, hence immutable) instance: the
master's heartbeat fast path stringifies ids hundreds of times per beat
(job-table keys, status folds, kill scans), and rebuilding the f-string
each time was profiling-visible at fleet scale.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True, order=True)
class JobID:
    cluster: str
    id: int

    def __str__(self) -> str:
        s = self.__dict__.get("_str")
        if s is None:
            s = f"job_{self.cluster}_{self.id:04d}"
            object.__setattr__(self, "_str", s)
        return s

    @classmethod
    def parse(cls, s: str) -> "JobID":
        _, cluster, n = s.rsplit("_", 2)
        return cls(cluster, int(n))


@dataclass(frozen=True, order=True)
class TaskID:
    job: JobID
    is_map: bool
    id: int

    def __str__(self) -> str:
        s = self.__dict__.get("_str")
        if s is None:
            kind = "m" if self.is_map else "r"
            s = (f"task_{self.job.cluster}_{self.job.id:04d}_{kind}_"
                 f"{self.id:06d}")
            object.__setattr__(self, "_str", s)
        return s

    @classmethod
    def parse(cls, s: str) -> "TaskID":
        parts = s.split("_")
        return cls(JobID(parts[1], int(parts[2])), parts[3] == "m", int(parts[4]))


@dataclass(frozen=True, order=True)
class TaskAttemptID:
    task: TaskID
    attempt: int

    def __str__(self) -> str:
        s = self.__dict__.get("_str")
        if s is None:
            t = self.task
            kind = "m" if t.is_map else "r"
            s = (f"attempt_{t.job.cluster}_{t.job.id:04d}_{kind}_"
                 f"{t.id:06d}_{self.attempt}")
            object.__setattr__(self, "_str", s)
        return s

    @classmethod
    def parse(cls, s: str) -> "TaskAttemptID":
        parts = s.split("_")
        tid = TaskID(JobID(parts[1], int(parts[2])), parts[3] == "m", int(parts[4]))
        return cls(tid, int(parts[5]))
