"""The old-API helper library ≈ ``org.apache.hadoop.mapred.lib``.

Components reproduced here (reference file in parens):

- trivial mappers: :class:`InverseMapper`, :class:`TokenCountMapper`,
  :class:`RegexMapper` (InverseMapper.java, TokenCountMapper.java,
  RegexMapper.java);
- :class:`FieldSelectionMapReduce` (FieldSelectionMapReduce.java) —
  cut(1)-style field selection with the reference's spec syntax
  ``"2,3-4:0-"`` (key fields : value fields, ``n-`` = n to end);
- :class:`KeyFieldBasedComparator` (KeyFieldBasedComparator.java /
  KeyFieldHelper.java) — Unix-sort ``-kPOS1[,POS2][nr]`` options over
  separated text keys, numeric and reverse per spec;
- :class:`ChainMapper` / :class:`ChainReducer` (Chain.java) — run a
  pipeline of mappers inside one task, [MAP+ / REDUCE MAP*];
- :class:`MultipleInputs` (MultipleInputs.java/DelegatingMapper.java) —
  per-input-path mapper dispatch (the generalization the datajoin
  contrib builds on);
- :class:`MultipleOutputs` (MultipleOutputs.java) — named side outputs
  written through the job's OutputFormat into the task work dir;
- the aggregate framework (lib/aggregate/ValueAggregator*.java):
  mappers emit ``("<TYPE>:<id>", value)`` records and
  :class:`ValueAggregatorReducer` folds them with the named aggregator
  (LongValueSum, DoubleValueSum, LongValueMax/Min, StringValueMax/Min,
  UniqValueCount, ValueHistogram); streaming's ``-reducer aggregate``
  resolves here, as the reference's does.

HashPartitioner / KeyFieldBasedPartitioner / Identity* /
MultithreadedMapRunner live in api.py; TotalOrderPartitioner in
total_order.py; NLineInputFormat / CombineFileInputFormat in
input_formats.py.
"""

from __future__ import annotations

import functools
import re
from typing import Any, Iterable

from tpumr.mapred.api import Mapper, OutputCollector, Reducer
from tpumr.utils.reflection import (class_name, new_instance,
                                    resolve_class)


class InverseMapper(Mapper):
    """(k, v) → (v, k) ≈ lib/InverseMapper.java."""

    def map(self, key, value, output, reporter):
        output.collect(value, key)


class TokenCountMapper(Mapper):
    """(_, text) → (token, 1) per whitespace token ≈ TokenCountMapper."""

    def map(self, key, value, output, reporter):
        text = value.decode("utf-8", "replace") \
            if isinstance(value, (bytes, bytearray)) else str(value)
        for tok in text.split():
            output.collect(tok, 1)


class RegexMapper(Mapper):
    """(_, text) → (match_group, 1) ≈ lib/RegexMapper.java; conf keys
    ``mapred.mapper.regex`` and ``mapred.mapper.regex.group``."""

    def configure(self, conf) -> None:
        self._re = re.compile(conf.get("mapred.mapper.regex", ""))
        self._group = conf.get_int("mapred.mapper.regex.group", 0)

    def map(self, key, value, output, reporter):
        text = value.decode("utf-8", "replace") \
            if isinstance(value, (bytes, bytearray)) else str(value)
        for m in self._re.finditer(text):
            output.collect(m.group(self._group), 1)


# ------------------------------------------------------- field selection


def _parse_field_spec(spec: str) -> "list[tuple[int, int | None]]":
    """"2,3-4,6-" → [(2,2),(3,4),(6,None)] (None = to the last field)."""
    out: "list[tuple[int, int | None]]" = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        lo, sep, hi = part.partition("-")
        if not sep:
            out.append((int(lo), int(lo)))
        else:
            out.append((int(lo), int(hi) if hi.strip() else None))
    return out


def _select(fields: "list[str]",
            ranges: "list[tuple[int, int | None]]") -> "list[str]":
    picked: "list[str]" = []
    for lo, hi in ranges:
        stop = len(fields) if hi is None else hi + 1
        picked.extend(fields[lo:stop])
    return picked


class FieldSelectionMapReduce(Mapper, Reducer):
    """≈ lib/FieldSelectionMapReduce.java: both phases split each record
    on ``mapred.data.field.separator`` (default TAB) and re-emit selected
    fields per ``mapred.text.key.value.fields.spec`` — the format is
    ``keyFieldsSpec:valueFieldsSpec`` with 0-based fields, e.g.
    ``"0,2:1-"``."""

    def configure(self, conf) -> None:
        self._sep = str(conf.get("mapred.data.field.separator", "\t"))
        spec = str(conf.get("mapred.text.key.value.fields.spec", "0:1-"))
        key_spec, _, val_spec = spec.partition(":")
        self._key_ranges = _parse_field_spec(key_spec)
        self._val_ranges = _parse_field_spec(val_spec)

    def _split(self, value) -> "list[str]":
        text = value.decode("utf-8", "replace") \
            if isinstance(value, (bytes, bytearray)) else str(value)
        return text.split(self._sep)

    def map(self, key, value, output, reporter):
        fields = self._split(value)
        output.collect(self._sep.join(_select(fields, self._key_ranges)),
                       self._sep.join(_select(fields, self._val_ranges)))

    def reduce(self, key, values, output, reporter):
        for v in values:
            output.collect(key, v)


# ---------------------------------------------------- key-field comparator


_KEY_OPT = re.compile(r"-k\s*(\d+)(?:\.(\d+))?(?:,(\d+)(?:\.(\d+))?)?([nr]*)")


@functools.total_ordering
class _SpecKey:
    """Orderable sort key honoring per-field numeric/reverse flags."""

    __slots__ = ("parts",)

    def __init__(self, parts: "list[tuple[Any, bool]]") -> None:
        self.parts = parts  # [(comparable, reverse), ...]

    def __eq__(self, other) -> bool:
        return self.parts == other.parts

    def __lt__(self, other) -> bool:
        for (a, rev), (b, _) in zip(self.parts, other.parts):
            if a == b:
                continue
            return (a > b) if rev else (a < b)
        return len(self.parts) < len(other.parts)


class KeyFieldBasedComparator:
    """≈ lib/KeyFieldBasedComparator.java: Unix-sort style key options
    from ``mapred.text.key.comparator.options``, e.g. ``-k2,2nr -k1,1``
    (1-based fields over ``map.output.key.field.separator``, default
    TAB; ``n`` = numeric, ``r`` = reverse). Plugs into the job's
    comparator seam (JobConf.set_output_key_comparator_class)."""

    def __init__(self, conf: Any = None) -> None:
        opts, self._sep = "", "\t"
        if conf is not None:
            opts = str(conf.get("mapred.text.key.comparator.options", ""))
            self._sep = str(conf.get("map.output.key.field.separator",
                                     "\t"))
        self._specs = []
        for m in _KEY_OPT.finditer(opts):
            if m.group(2) or m.group(4):
                raise ValueError(
                    f"char offsets in {m.group(0)!r} are not supported — "
                    "use whole-field specs (-kPOS1[,POS2][nr])")
            # sort(1) semantics: '-k2' = field 2 through END of key;
            # '-k2,2' = field 2 only
            end = int(m.group(3)) if m.group(3) else 10 ** 9
            self._specs.append((int(m.group(1)), end,
                                "n" in m.group(5), "r" in m.group(5)))
        self._specs = self._specs or [(1, 10 ** 9, False, False)]

    def configure(self, conf) -> None:  # JobConfigurable seam
        self.__init__(conf)

    def sort_key(self, kbytes: bytes):
        from tpumr.io.writable import deserialize
        key = deserialize(kbytes)
        text = key.decode("utf-8", "replace") \
            if isinstance(key, (bytes, bytearray)) else str(key)
        fields = text.split(self._sep)
        parts: "list[tuple[Any, bool]]" = []
        for start, end, numeric, rev in self._specs:
            sel = self._sep.join(fields[start - 1:end])
            if numeric:
                try:
                    val: Any = (1, float(sel))
                except ValueError:
                    val = (0, 0.0)  # non-numeric sorts first, like sort -n
                parts.append((val, rev))
            else:
                parts.append((sel, rev))
        return _SpecKey(parts)


# ----------------------------------------------------------------- chain


def _chain_step(mapper: Mapper, downstream: Any, reporter: Any,
                key: Any, value: Any) -> None:
    mapper.map(key, value, downstream, reporter)


class ChainMapper(Mapper):
    """≈ lib/ChainMapper.java: run mappers in sequence inside one map
    task — each mapper's collect feeds the next's map; the last one's
    output reaches the real collector. Configure with
    :meth:`add_mapper` or the ``tpumr.chain.mappers`` conf key (list of
    class names)."""

    CONF_KEY = "tpumr.chain.mappers"

    @staticmethod
    def add_mapper(conf: Any, mapper_cls: type) -> None:
        chain = list(conf.get(ChainMapper.CONF_KEY) or [])
        chain.append(class_name(mapper_cls))
        conf.set(ChainMapper.CONF_KEY, chain)
        conf.set_mapper_class(ChainMapper)

    def configure(self, conf) -> None:
        names = conf.get(self.CONF_KEY) or []
        if not names:
            raise ValueError(f"{self.CONF_KEY} is empty — add_mapper first")
        self._chain = [new_instance(resolve_class(n), conf) for n in names]
        self._wired: "tuple[Any, OutputCollector] | None" = None

    def _first_collector(self, output, reporter) -> OutputCollector:
        # wire the pipeline ONCE per (task, output): collectors are fixed
        # for the task's lifetime, and map() is the per-record hot loop
        if self._wired is None or self._wired[0] is not output:
            nxt: Any = output
            for mapper in reversed(self._chain[1:]):
                nxt = OutputCollector(functools.partial(
                    _chain_step, mapper, nxt, reporter))
            self._wired = (output, nxt)
        return self._wired[1]

    def map(self, key, value, output, reporter):
        self._chain[0].map(key, value,
                           self._first_collector(output, reporter),
                           reporter)

    def close(self) -> None:
        for m in self._chain:
            m.close()


class ChainReducer(Reducer):
    """≈ lib/ChainReducer.java: one reducer, then a chain of mappers over
    its output ([REDUCE MAP*])."""

    REDUCER_KEY = "tpumr.chain.reducer"
    MAPPERS_KEY = "tpumr.chain.reduce.mappers"

    @staticmethod
    def set_reducer(conf: Any, reducer_cls: type) -> None:
        conf.set(ChainReducer.REDUCER_KEY, class_name(reducer_cls))
        conf.set_reducer_class(ChainReducer)

    @staticmethod
    def add_mapper(conf: Any, mapper_cls: type) -> None:
        chain = list(conf.get(ChainReducer.MAPPERS_KEY) or [])
        chain.append(class_name(mapper_cls))
        conf.set(ChainReducer.MAPPERS_KEY, chain)

    def configure(self, conf) -> None:
        name = conf.get(self.REDUCER_KEY)
        if not name:
            raise ValueError(f"{self.REDUCER_KEY} unset — set_reducer first")
        self._reducer = new_instance(resolve_class(name), conf)
        self._mappers = [new_instance(resolve_class(n), conf)
                         for n in (conf.get(self.MAPPERS_KEY) or [])]
        self._wired: "tuple[Any, OutputCollector] | None" = None

    def reduce(self, key, values, output, reporter):
        if self._wired is None or self._wired[0] is not output:
            nxt: Any = output
            for mapper in reversed(self._mappers):
                nxt = OutputCollector(functools.partial(
                    _chain_step, mapper, nxt, reporter))
            self._wired = (output, nxt)
        self._reducer.reduce(key, values, self._wired[1], reporter)

    def close(self) -> None:
        self._reducer.close()
        for m in self._mappers:
            m.close()


# ------------------------------------------------------- multiple inputs


class MultipleInputs:
    """≈ lib/MultipleInputs.java: per-input-path mapper classes, routed
    by the split's source path (DelegatingMapper role). Input formats
    stay job-global (the reference's per-path InputFormat variant is
    subsumed by path-specific jobs here — documented divergence)."""

    CONF_KEY = "tpumr.multiple.inputs"

    @staticmethod
    def add_input_path(conf: Any, path: str, mapper_cls: type) -> None:
        table = dict(conf.get(MultipleInputs.CONF_KEY) or {})
        table[str(path).rstrip("/")] = class_name(mapper_cls)
        conf.set(MultipleInputs.CONF_KEY, table)
        existing = conf.get_strings("mapred.input.dir")
        if str(path) not in existing:
            conf.set_input_paths(*(list(existing) + [str(path)]))
        conf.set_mapper_class(DelegatingMapper)


class DelegatingMapper(Mapper):
    """Routes records to the mapper registered for the split's path
    (boundary-respecting longest-prefix match, like contrib.datajoin)."""

    def configure(self, conf) -> None:
        self._conf = conf
        self._table = {p: resolve_class(n) for p, n in
                       (conf.get(MultipleInputs.CONF_KEY) or {}).items()}
        self._delegate: "Mapper | None" = None

    def _resolve(self) -> Mapper:
        if self._delegate is None:
            path = str(self._conf.get("tpumr.task.input.path") or "")
            best = None
            for prefix, cls in self._table.items():
                if (path == prefix or path.startswith(prefix + "/")) and \
                        (best is None or len(prefix) > len(best[0])):
                    best = (prefix, cls)
            if best is None:
                raise ValueError(f"no mapper registered for split path "
                                 f"{path!r} (inputs: {sorted(self._table)})")
            self._delegate = new_instance(best[1], self._conf)
        return self._delegate

    def map(self, key, value, output, reporter):
        self._resolve().map(key, value, output, reporter)

    def close(self) -> None:
        if self._delegate is not None:
            self._delegate.close()


# ------------------------------------------------------ multiple outputs


class MultipleOutputs:
    """≈ lib/MultipleOutputs.java: named side outputs next to the task's
    main output, through the job's OutputFormat and the same committer
    work dir (so side files follow the job's two-phase commit). Usage::

        mo = MultipleOutputs(conf)
        mo.collector("errors", reporter).collect(k, v)
        ...
        mo.close()
    """

    def __init__(self, conf: Any) -> None:
        self._conf = conf
        self._writers: dict[str, Any] = {}

    def _work_dir(self) -> str:
        wd = self._conf.get("tpumr.task.work.dir")
        if not wd:
            raise ValueError("MultipleOutputs needs tpumr.task.work.dir "
                             "(set by the task runtime)")
        from tpumr.fs.filesystem import FileSystem
        FileSystem.get(wd, self._conf).mkdirs(wd)  # lazy: only when used
        return wd

    def collector(self, name: str, reporter: Any = None) -> OutputCollector:
        if not re.fullmatch(r"[A-Za-z0-9]+", name) or name == "part":
            raise ValueError(f"bad MultipleOutputs name {name!r} "
                             "(alphanumeric, not 'part' — that is the "
                             "main output's prefix)")
        w = self._writers.get(name)
        if w is None:
            out_fmt = new_instance(self._conf.get_output_format(),
                                   self._conf)
            # -1 = framework never stamped a partition (off-framework
            # use); part files then number from 0
            part = max(0, self._conf.get_int("tpumr.task.partition", -1))
            w = self._writers[name] = out_fmt.get_record_writer(
                self._conf, self._work_dir(), part, prefix=name)
        return OutputCollector(w.write)

    def close(self) -> None:
        for w in self._writers.values():
            w.close()


# -------------------------------------------------------------- aggregate


class _Agg:
    def add(self, v) -> None:
        raise NotImplementedError

    def result(self):
        raise NotImplementedError


class _Sum(_Agg):
    def __init__(self, cast):
        self.cast, self.total = cast, cast(0)

    def add(self, v):
        self.total += self.cast(v)

    def result(self):
        return self.total


class _MinMax(_Agg):
    def __init__(self, cast, is_max: bool):
        self.cast, self.is_max, self.cur = cast, is_max, None

    def add(self, v):
        v = self.cast(v)
        if self.cur is None or (v > self.cur if self.is_max else v < self.cur):
            self.cur = v

    def result(self):
        return self.cur


class _UniqCount(_Agg):
    def __init__(self):
        self.seen: set = set()

    def add(self, v):
        self.seen.add(str(v))

    def result(self):
        return len(self.seen)


class _Histogram(_Agg):
    def __init__(self):
        from collections import Counter
        self.counts: Any = Counter()

    def add(self, v):
        self.counts[str(v)] += 1

    def result(self):
        items = sorted(self.counts.items())
        return ";".join(f"{k}:{n}" for k, n in items)


AGGREGATORS = {
    "LongValueSum": lambda: _Sum(int),
    "DoubleValueSum": lambda: _Sum(float),
    "LongValueMax": lambda: _MinMax(int, True),
    "LongValueMin": lambda: _MinMax(int, False),
    "StringValueMax": lambda: _MinMax(str, True),
    "StringValueMin": lambda: _MinMax(str, False),
    "UniqValueCount": lambda: _UniqCount(),
    "ValueHistogram": lambda: _Histogram(),
}


def _agg_for(key: str) -> "tuple[_Agg, str]":
    agg_type, sep, ident = str(key).partition(":")
    maker = AGGREGATORS.get(agg_type)
    if not sep or maker is None:
        raise ValueError(
            f"aggregate key {key!r} is not '<type>:<id>' with type in "
            f"{sorted(AGGREGATORS)}")
    return maker(), ident


class ValueAggregatorReducer(Reducer):
    """≈ lib/aggregate/ValueAggregatorReducer.java: the mapper emits
    ``("<TYPE>:<id>", value)``; this folds each group with the named
    aggregator and emits (id, result). Streaming's ``-reducer
    aggregate`` resolves here."""

    def reduce(self, key, values, output, reporter):
        agg, ident = _agg_for(key)
        for v in values:
            agg.add(v)
        output.collect(ident, agg.result())


class ValueAggregatorCombiner(Reducer):
    """Partial fold for the distributive aggregators; pass-through (key
    kept) so the reducer still sees '<TYPE>:<id>' keys."""

    DISTRIBUTIVE = {"LongValueSum", "DoubleValueSum", "LongValueMax",
                    "LongValueMin", "StringValueMax", "StringValueMin"}

    def reduce(self, key, values, output, reporter):
        agg_type = str(key).partition(":")[0]
        if agg_type not in self.DISTRIBUTIVE:
            for v in values:  # uniq/histogram need every raw value
                output.collect(key, v)
            return
        agg, _ = _agg_for(key)
        for v in values:
            agg.add(v)
        output.collect(key, agg.result())
