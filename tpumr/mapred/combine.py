"""Streaming combiner-at-merge — one key group resident at a time.

≈ the reference's ``Task.CombinerRunner`` used inside ``sortAndSpill``
and ``mergeParts`` (MapTask.java:1396,1621) and at shuffle-merge time
(ReduceTask's InMemFSMergeThread). The seed materialized whole
partitions (``self._combine(list(merged))``) before combining — on a
wide merge that is the entire partition in Python lists. This helper
groups the already-sorted stream run-at-a-time instead: memory is
bounded by the largest single key group, never the partition.

Combiner lifecycle keeps Hadoop semantics (instantiated per use, closed
deterministically) and tolerates subprocess-backed combiners
(streaming.StreamCombiner) that emit output only when the child
finishes: records buffered by the collector are yielded as they appear,
and anything the combiner flushes at ``close()`` is drained afterward.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator

from tpumr.core.counters import TaskCounter
from tpumr.io.writable import deserialize, serialize


def combined_stream(conf: Any, combiner_cls: type,
                    sort_key: "Callable[[bytes], Any] | None",
                    stream: Iterable[tuple[bytes, bytes]],
                    reporter: Any) -> Iterator[tuple[bytes, bytes]]:
    """Run ``combiner_cls`` over a SORTED raw (kbytes, vbytes) stream,
    yielding combined raw records group by group. ``sort_key`` is the
    grouping comparator seam (None = group on raw key bytes, the
    RawComparator case)."""
    from tpumr.mapred.api import OutputCollector
    from tpumr.utils.reflection import new_instance

    out: "list[tuple[bytes, bytes]]" = []
    collector = OutputCollector(
        lambda k, v: out.append((serialize(k), serialize(v))))
    combiner = new_instance(combiner_cls, conf)
    n_in = 0
    n_out = 0
    closed = False
    it = iter(stream)
    try:
        try:
            kb, vb = next(it)
        except StopIteration:
            kb = None  # type: ignore[assignment]
        while kb is not None:
            group: "list[bytes]" = [vb]
            group_sk = sort_key(kb) if sort_key is not None else kb
            first_kb = kb
            try:
                while True:
                    nkb, nvb = next(it)
                    if (sort_key(nkb) if sort_key is not None
                            else nkb) != group_sk:
                        break
                    group.append(nvb)
            except StopIteration:
                nkb = None  # type: ignore[assignment]
                nvb = b""
            n_in += len(group)
            key = deserialize(first_kb)
            # the group is already materialized, so a combiner that
            # stops early needs no drain — unconsumed values just drop
            values = (deserialize(v) for v in group)
            combiner.reduce(key, values, collector, reporter)
            if out:
                n_out += len(out)
                yield from out
                out.clear()
            kb, vb = nkb, nvb
        closed = True
        combiner.close()
        # subprocess combiners flush on close — drain the tail
        if out:
            n_out += len(out)
            yield from out
            out.clear()
    finally:
        if not closed:
            combiner.close()
        if reporter is not None:
            reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                  TaskCounter.COMBINE_INPUT_RECORDS, n_in)
            reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                  TaskCounter.COMBINE_OUTPUT_RECORDS, n_out)
