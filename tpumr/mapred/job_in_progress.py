"""Per-job task bookkeeping + per-backend runtime profiling.

≈ ``org.apache.hadoop.mapred.JobInProgress`` (reference: src/mapred/org/
apache/hadoop/mapred/JobInProgress.java, 3713 LoC). The pieces that matter
to the hybrid scheduler are carried exactly:

- ``finishedCPUMapTasks`` / ``finishedGPUMapTasks`` counters
  (JobInProgress.java:115-116, incremented :2779-2784) →
  :attr:`finished_cpu_maps` / :attr:`finished_tpu_maps`;
- ``getCPUMapTaskMeanTime()`` / ``getGPUMapTaskMeanTime()``
  (:527-565) → :meth:`cpu_map_mean_time` / :meth:`tpu_map_mean_time` —
  kept as RUNNING sums + EWMA instead of the reference's per-heartbeat
  O(tasks) recomputation over all TaskReports (the control-plane hot-loop
  cost called out in SURVEY.md §3.2; semantics preserved, cost O(1));
- locality caches (node → pending maps) feeding
  ``obtainNewNodeLocalMapTask`` / ``obtainNewNonLocalMapTask``;
- the reference decrements BOTH backend counters on a failed map
  (JobInProgress.java:3156-3159) — a quirk, not intent; here a failure
  decrements only the backend the attempt ran on (divergence documented).
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any

from tpumr.core.counters import Counters
from tpumr.mapred.ids import JobID, TaskAttemptID, TaskID
from tpumr.mapred.task import (Task, TaskPhase, TaskReport, TaskState,
                               TaskStatus)
from tpumr.core import confkeys
from tpumr.metrics.locks import RANK_JOB, InstrumentedRLock


class CompletionEventFeed:
    """Append-only completion-event feed with LOCK-FREE reads.

    Writers — the master's status fold, under the job lock — only ever
    ``append()`` or flip an existing event's ``status`` value in place
    (the OBSOLETE withdrawal mark); events are never removed or
    reordered, so an index, once served, names the same event forever.
    Readers slice by cursor WITHOUT any lock: under CPython's GIL a
    list slice concurrent with appends returns a consistent prefix, and
    an in-place ``status`` overwrite is a single atomic value store on
    a dict whose shape never changes. A reader racing a withdrawal sees
    either SUCCEEDED (and later the appended tombstone at a higher
    index) or OBSOLETE directly — both orderings the PR-1 protocol
    already handles. This is what lets ``get_map_completion_events``
    serve reducer polls while the fold appends, with neither touching
    the job lock (PR 8's lock decomposition).
    """

    __slots__ = ("_events",)

    def __init__(self) -> None:
        self._events: "list[dict]" = []

    def append(self, event: dict) -> None:
        self._events.append(event)

    def read(self, from_index: int, max_events: int) -> "tuple[list, int]":
        """One cursor-based incremental poll: up to ``max_events``
        events from ``from_index``, plus the backlog REMAINING after
        this batch (0 when the poll fully caught up — the lag series
        must measure what a poller couldn't drain, not the volume it
        drained fine, or it grows with job width forever)."""
        total = len(self._events)
        frm = max(0, int(from_index))
        if frm > total:
            # a cursor minted against a PREVIOUS incarnation of this
            # job's feed (master restart → the resubmitted job re-feeds
            # recovered events from 0): an append-only feed can never be
            # shorter than a cursor it issued, so serve the WHOLE feed —
            # client folds are idempotent, and a stale cursor must never
            # silently skip recovered or fresh events
            frm = 0
        events = self._events[frm:frm + max(0, int(max_events))]
        return events, max(0, total - frm - len(events))

    def __len__(self) -> int:
        return len(self._events)

    def __getitem__(self, i: Any) -> Any:
        return self._events[i]

    def __iter__(self) -> Any:
        return iter(self._events)


class JobState:
    PREP = "PREP"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    KILLED = "KILLED"
    TERMINAL = {SUCCEEDED, FAILED, KILLED}


#: ≈ mapred/JobPriority.java — ordinal order is scheduling order
JOB_PRIORITIES = ("VERY_HIGH", "HIGH", "NORMAL", "LOW", "VERY_LOW")


def normalize_priority(value: Any) -> str:
    """Validate/canonicalize a priority name (case-insensitive; the
    reference's JobPriority.valueOf raises on unknowns — so do we)."""
    p = str(value).upper()
    if p not in JOB_PRIORITIES:
        raise ValueError(f"unknown job priority {value!r}; one of "
                         f"{', '.join(JOB_PRIORITIES)}")
    return p


def priority_rank(priority: str) -> int:
    """Sort key: lower rank schedules first."""
    return JOB_PRIORITIES.index(priority)


@dataclass
class TaskInProgress:
    """≈ mapred/TaskInProgress.java (condensed): one logical task, its
    attempts and state."""

    task_id: TaskID
    partition: int
    split: dict | None = None
    state: str = "pending"            # pending | running | succeeded | failed
    attempts: dict[str, TaskStatus] = field(default_factory=dict)
    next_attempt: int = 0
    failures: int = 0
    #: device/compile-classed failures of TPU attempts — the TPU→CPU
    #: demotion ledger (counted separately from ``failures`` because a
    #: demoted TIP keeps its normal attempt budget for the CPU re-runs)
    tpu_failures: int = 0
    successful_attempt: str = ""
    report: TaskReport = None  # type: ignore[assignment]
    # --- scheduling feedback (master-local, MONOTONIC domain — never
    # --- mixed with the wall stamps the client-visible report carries) ---
    #: monotonic stamp of the current incarnation's first dispatch; 0.0
    #: until assigned (and again after a requeue re-pends the TIP)
    dispatch_mono: float = 0.0
    #: EWMA of progress units per second, folded from heartbeat statuses
    rate_ewma: float = 0.0
    #: best progress seen across the incarnation's attempts, and when
    last_progress: float = 0.0
    last_progress_mono: float = 0.0

    def __post_init__(self) -> None:
        if self.report is None:
            self.report = TaskReport(self.task_id)

    def new_attempt(self) -> TaskAttemptID:
        a = TaskAttemptID(self.task_id, self.next_attempt)
        self.next_attempt += 1
        return a

    def reset_feedback(self) -> None:
        """Requeue: the next dispatch starts a fresh incarnation whose
        age and progress rate must not inherit the dead attempt's."""
        self.dispatch_mono = 0.0
        self.rate_ewma = 0.0
        self.last_progress = 0.0
        self.last_progress_mono = 0.0

    @property
    def is_map(self) -> bool:
        return self.task_id.is_map

    def running_attempts(self) -> list[TaskStatus]:
        return [s for s in self.attempts.values()
                if s.state == TaskState.RUNNING]


class JobInProgress:
    def __init__(self, job_id: JobID, conf_dict: dict, splits: list[dict],
                 tracker_addr_of: Any = None) -> None:
        self.job_id = job_id
        self.conf = dict(conf_dict)
        self.num_reduces = confkeys.get_int(self.conf,
                                            "mapred.reduce.tasks")
        self.state = JobState.RUNNING
        self.start_time = time.time()
        self.finish_time = 0.0
        self.counters = Counters()
        # rank-ordered (metrics/locks.py): the job lock is the BOTTOM of
        # the master's lock order — the status fold and the scheduler's
        # obtain calls take it while holding nothing above it, and
        # nothing acquired under it may reach back up (scheduler → job,
        # never the reverse; asserted in debug mode)
        self.lock = InstrumentedRLock(name=f"job-{job_id}", rank=RANK_JOB)
        self.max_map_attempts = confkeys.get_int(
            self.conf, "mapred.map.max.attempts")
        self.max_reduce_attempts = confkeys.get_int(
            self.conf, "mapred.reduce.max.attempts")
        #: distinct reducers that must report a map attempt's output
        #: unfetchable before the master re-executes the map
        #: (≈ JobInProgress.fetchFailureNotification's
        #: MAX_FETCH_FAILURES_NOTIFICATIONS)
        self.max_fetch_failures_per_map = confkeys.get_int(
            self.conf, "mapred.max.fetch.failures.per.map")
        self.slowstart = confkeys.get_float(
            self.conf, "mapred.reduce.slowstart.completed.maps")
        self.speculative = confkeys.get_boolean(
            self.conf, "mapred.speculative.execution")
        #: lazily memoized has_kernel() answer (kernel conf is submit-fixed)
        self._has_kernel: "bool | None" = None
        # ≈ mapred.reduce.tasks.speculative.execution: reduces speculate
        # too (JobInProgress.java:257,739,2320 hasSpeculativeReduces /
        # findSpeculativeTask) — a straggling reduce ends every job, so
        # it needs the same mitigation maps get. Defaults to the global
        # switch; the dedicated key turns one side off independently.
        spec_reduces = confkeys.get_boolean(
            self.conf, "mapred.reduce.speculative.execution")
        self.speculative_reduces = self.speculative \
            if spec_reduces is None else spec_reduces
        # ≈ JobPriority (mapred/JobPriority.java) — FIFO scheduling
        # sorts by (priority, start time); mutable at runtime via
        # JobMaster.set_job_priority (hadoop job -set-priority)
        self.priority = normalize_priority(
            confkeys.get(self.conf, "mapred.job.priority"))
        # scenario lab: a job tagged with a traffic class gets per-class
        # submit→first-assignment / submit→complete latency series on
        # the master, which the flight recorder windows into per-class
        # SLO verdicts. Sanitized: the tag becomes a metric label.
        cls = str(confkeys.get(self.conf, "tpumr.scenario.class") or "")
        self.traffic_class = re.sub(r"[^a-z0-9_]", "_",
                                    cls.lower())[:24]
        self.submit_mono = time.monotonic()
        self.first_assign_mono: "float | None" = None
        #: master brownout: True pauses speculative scans for this job
        #: (stamped at submit while shedding, flipped on running jobs
        #: at level transitions; speculation is pure opportunism and
        #: the first deferrable scheduler cost)
        self.speculation_hold = False
        self.error = ""

        self.maps = [TaskInProgress(TaskID(job_id, True, i), i, split=s)
                     for i, s in enumerate(splits)]
        self.reduces = [TaskInProgress(TaskID(job_id, False, r), r)
                        for r in range(self.num_reduces)]
        # locality caches ≈ nonRunningMapCache: host -> splits and
        # rack -> splits (the rack tier of obtainNewNodeOrRackLocalMapTask)
        from tpumr.net import DEFAULT_RACK, resolver_from_conf
        self._rack_resolver = resolver_from_conf(self.conf)
        self._default_rack = DEFAULT_RACK
        self.host_cache: dict[str, set[int]] = {}
        self.rack_cache: dict[str, set[int]] = {}
        for i, s in enumerate(splits):
            for h in (s or {}).get("locations", []) or []:
                self.host_cache.setdefault(h, set()).add(i)
                rack = self._rack_resolver(h)
                if rack != DEFAULT_RACK:
                    self.rack_cache.setdefault(rack, set()).add(i)
        self._pending_maps = set(range(len(self.maps)))
        self._pending_reduces = set(range(self.num_reduces))
        self.finished_maps = 0
        self.finished_reduces = 0
        #: attempts whose terminal outcome is already in the history log
        #: (heartbeat replays re-deliver terminal statuses)
        self.history_logged: set[str] = set()
        self.speculative_map_tasks = 0
        self.speculative_reduce_tasks = 0
        # --- scheduling feedback: targeted (LATE-style) speculation ---
        #: False = legacy blanket twins (the reference's age-only rule)
        self.speculative_targeted = confkeys.get_boolean(
            self.conf, "tpumr.speculative.targeted")
        #: concurrent speculative attempts allowed in flight per job
        self.speculative_cap = max(1, confkeys.get_int(
            self.conf, "tpumr.speculative.cap"))
        #: critical-path membership: a TIP whose remaining estimate is
        #: within this fraction of the job's longest remaining estimate
        self._spec_cp_fraction = confkeys.get_float(
            self.conf, "tpumr.speculative.critical.fraction")
        #: per-TIP progress-rate EWMA weight
        self._rate_alpha = confkeys.get_float(
            self.conf, "tpumr.speculative.rate.ewma")
        #: outcome counters: launched at obtain time; won/wasted settle
        #: when the speculative attempt reaches a terminal state
        self.speculative_launched = 0
        self.speculative_won = 0
        self.speculative_wasted = 0
        #: speculative attempts not yet terminal (the in-flight gauge);
        #: mutated only under ``lock``, len() read lock-free by gauges
        self._spec_attempts: set[str] = set()
        #: memoized devcache_tags() answer (side-input conf is
        #: submit-fixed; the affinity scheduler asks per TPU pass)
        self._devcache_tags: "tuple[str, ...] | None" = None
        #: running sum of successful reduce runtimes — the speculation
        #: threshold's mean (reduces have no per-backend split: they
        #: always run on CPU slots)
        self._reduce_time_sum = 0.0
        #: set by the master once job-level output commit/abort completed —
        #: clients must not observe a terminal state before the output is
        #: actually promoted (finalization runs outside the heartbeat lock)
        self.finalized = threading.Event()
        #: atomic claim (under ``lock``) that finalization is running —
        #: kill_job racing a heartbeat-deferred finalize must not run
        #: commit/abort twice or duplicate JOB_FINISHED history events
        self.finalize_started = False
        #: attempts a scheduler marked for preemption (kill-not-fail);
        #: cleared when the attempt's terminal status arrives
        self._preempt_requested: set[str] = set()
        #: RUNNING attempts with a kill pending (speculative-race
        #: losers, preemptions, operator kills) — maintained at the
        #: points where an attempt BECOMES a kill candidate so the
        #: heartbeat kill scan is a lock-free set probe instead of a
        #: per-attempt job-lock round trip re-deriving it every beat
        self._kill_marked: set[str] = set()
        #: attempts whose operator kill must count as FAILED (-fail-task)
        self._fail_requested: set[str] = set()
        # --- per-backend profiling (running sums, O(1) per update) ---
        self.finished_cpu_maps = 0
        self.finished_tpu_maps = 0
        self._cpu_time_sum = 0.0
        self._tpu_time_sum = 0.0
        self._ewma_alpha = confkeys.get_float(self.conf,
                                              "tpumr.profile.ewma")
        self._cpu_ewma = 0.0
        self._tpu_ewma = 0.0
        # completion events for reduce fetchers (≈ TaskCompletionEvents).
        # APPEND-ONLY: consumers read incrementally by cursor, so a
        # withdrawn map output is marked status=OBSOLETE in place AND
        # re-announced as a tombstone event — never removed (removal
        # would shift indices under every live cursor). The feed object
        # makes reducer polls lock-free against the appending fold.
        self.completion_events = CompletionEventFeed()
        #: map attempt -> distinct reduce attempts reporting its output
        #: unfetchable (the "too many fetch failures" ledger)
        self._fetch_failures: dict[str, set[str]] = {}
        # --- pipeline streamed handoff (DAG engine) ---
        #: does this stage tee reduce output into IFile framing served
        #: over the shuffle wire for a downstream stage? Gated off for
        #: run shapes whose trackers never REGISTER a tee (process
        #: isolation drops the child's payload; device-shuffle reduces
        #: bypass run_reduce_task) — announcing addresses nothing
        #: serves would have every downstream map burn doomed fetch
        #: RPCs until the DFS fallback appears
        from tpumr.mapred.device_shuffle import DEVICE_SHUFFLE_KEY
        self.stream_handoff = (
            confkeys.get_boolean(self.conf,
                                 "tpumr.pipeline.stream.handoff")
            and str(self.conf.get("tpumr.task.isolation")
                    or "thread") != "process"
            and not bool(self.conf.get(DEVICE_SHUFFLE_KEY)))
        #: reduce-commit announcements for downstream stages — the SAME
        #: append-only feed class (and OBSOLETE-withdrawal dialect) the
        #: map completion events use, with ``map_index`` carrying the
        #: reduce PARTITION; served lock-free by
        #: get_handoff_completion_events
        self.handoff_events = CompletionEventFeed()
        #: scheduler FIFO anchor: normally the submit time, but stage
        #: jobs of a pipeline inherit the PIPELINE's submit time so a
        #: late stage never queues behind independent jobs submitted
        #: mid-pipeline (the master stamps it at submit)
        self.sched_anchor = self.start_time
        # --- accelerator fault tolerance (tentpole PR 4) ---
        #: device/compile-classed failures a TIP may take before it is
        #: pinned CPU-only (≈ "how many TPU retries does a sick kernel
        #: placement get"); ≥1 — 0 would demote before any failure
        self.tpu_attempt_retries = max(1, int(self.conf.get(
            "tpumr.tpu.attempt.retries", 1)))
        #: distinct device-failing TIPs before the whole JOB's TPU pass
        #: is quarantined off
        self.tpu_quarantine_tips = max(1, int(self.conf.get(
            "tpumr.tpu.job.quarantine.tips", 3)))
        #: job-level TPU quarantine flag: the scheduler's TPU pass and
        #: the optional-scheduling starvation gate both honor it (the
        #: gate MUST, or a quarantined job deadlocks with zero CPU
        #: budget and an ineligible TPU pass)
        self.tpu_disabled = False
        #: map partitions pinned CPU-only after repeated device-classed
        #: failures — the TPU obtain path skips them
        self._cpu_only_maps: set[int] = set()
        #: distinct TIPs that ever took a device-classed TPU failure
        #: (the job-quarantine threshold counts TIPs, not attempts)
        self._tpu_failed_tips: set[int] = set()
        #: demotion/quarantine decisions made inside update_task_status,
        #: drained by the master's heartbeat for metrics + history +
        #: trace instants (the JIP has no tracer/history of its own)
        self._accel_events: list[dict] = []
        #: per-assignment backend placement: (seconds-since-submit, 'T'|'c')
        #: appended at every map assignment — the raw series behind the
        #: hybrid scheduler's convergence curve, so ANY run's status or
        #: history doubles as the convergence artifact (SURVEY §5: backend
        #: placement is a first-class metric). Bounded; overflow counted.
        self.placement_series: list = []
        self.placement_dropped = 0
        #: raw successful-attempt runtimes, kept verbatim for the
        #: per-job stats rollup (metrics-<jobid>.json): the profile
        #: sums above are means the SCHEDULER needs (and unwind on
        #: quarantine); the rollup wants exact percentiles over what
        #: actually ran, quarantined or not. Bounded; overflow counted.
        self.map_runtimes: "list[tuple[float, bool]]" = []  # (s, on_tpu)
        self.reduce_runtimes: "list[float]" = []
        self.runtimes_dropped = 0
        #: distributed tracing (core/tracing.py): the job's trace id and
        #: the open root span, set by the master at submit for traced
        #: jobs only ("" / None keeps every trace check a cheap miss)
        self.trace_id: str = str(self.conf.get("tpumr.trace.id", "") or "")
        self.trace_root: Any = None
        # --- master restart survival (attempt-level recovery) ---
        #: the interrupted job this one was recovered from (None for a
        #: normal submission): attempt ids carrying the OLD job id are
        #: accepted as this job's own — recovered completion events,
        #: adopted in-flight attempts, and their fetch-failure reports
        #: all name old-id attempts
        self.recovered_from: "str | None" = None
        #: monotonic deadline before which the scheduler must NOT hand
        #: out this job's tasks (obtain_* return None): the recovery
        #: grace window. A restarted master sees pending TIPs whose
        #: attempts are still RUNNING on trackers that have not
        #: re-joined yet — assigning them would duplicate in-flight
        #: work (≈ the reference RecoveryManager waiting for trackers
        #: to report back before scheduling resumes)
        self.schedule_hold_until = 0.0

    # ------------------------------------------------------------ queries

    @property
    def num_maps(self) -> int:
        return len(self.maps)

    def pending_map_count(self) -> int:
        return len(self._pending_maps)

    def pending_reduce_count(self) -> int:
        return len(self._pending_reduces)

    def running_map_count(self) -> int:
        """Maps assigned and not yet finished (scheduler's usage signal)."""
        return max(0, len(self.maps) - self.finished_maps
                   - self.pending_map_count())

    def running_reduce_count(self) -> int:
        return max(0, len(self.reduces) - self.finished_reduces
                   - self.pending_reduce_count())

    def has_kernel(self) -> bool:
        """≈ the hadoop.pipes.gpu.executable gate
        (JobQueueTaskScheduler.java:342-347): only jobs with a device kernel
        OR a TPU pipes executable are eligible for TPU slots. Memoized —
        the kernel conf is fixed at submit, and the scheduler consults
        this per job per pass on the heartbeat fast path."""
        v = self._has_kernel
        if v is None:
            v = self._has_kernel = bool(
                self.conf.get("tpumr.map.kernel")
                or self.conf.get("tpumr.pipes.tpu.executable"))
        return v

    def tpu_eligible(self) -> bool:
        """May the scheduler's TPU pass offer this job work? The kernel
        gate plus the job-level accelerator quarantine."""
        return self.has_kernel() and not self.tpu_disabled

    def cpu_pinned_pending_count(self) -> int:
        """Pending maps that can ONLY run on CPU (demoted TIPs) — the
        optional-scheduling starvation gate must not zero the CPU budget
        while any of these exist, or they can never be assigned."""
        with self.lock:
            return len(self._pending_maps & self._cpu_only_maps)

    def has_accel_events(self) -> bool:
        """Lock-free emptiness hint so the heartbeat fold can skip the
        drain's lock round trip on the (overwhelmingly common) beat
        with no demotion/quarantine decisions. May be stale by one
        beat; the next fold drains whatever it missed."""
        return bool(self._accel_events)

    def drain_accel_events(self) -> "list[dict]":
        """Demotion/quarantine decisions since the last drain (consumed
        by the master heartbeat for metrics, history, and traces)."""
        with self.lock:
            out, self._accel_events = self._accel_events, []
            return out

    def cpu_map_mean_time(self) -> float:
        """Mean CPU map runtime (0.0 when no data — matching the reference's
        'returns 0 until first completion' behavior that makes the scheduler
        fall back to unconditional assignment)."""
        if self._ewma_alpha and self._cpu_ewma:
            return self._cpu_ewma
        return self._cpu_time_sum / self.finished_cpu_maps \
            if self.finished_cpu_maps else 0.0

    def tpu_map_mean_time(self) -> float:
        if self._ewma_alpha and self._tpu_ewma:
            return self._tpu_ewma
        return self._tpu_time_sum / self.finished_tpu_maps \
            if self.finished_tpu_maps else 0.0

    def acceleration_factor(self) -> float:
        """cpuMean / tpuMean (JobQueueTaskScheduler.java:175-178); 1.0 until
        both backends have profile data — and again after a job-level TPU
        quarantine (the unwound sums must not resurrect via in-flight
        TPU completions trickling in post-quarantine)."""
        if self.tpu_disabled:
            return 1.0
        cpu, tpu = self.cpu_map_mean_time(), self.tpu_map_mean_time()
        if cpu > 0 and tpu > 0:
            return cpu / tpu
        return 1.0

    def map_progress(self) -> float:
        if not self.maps:
            return 1.0
        running = sum(max((s.progress for s in t.running_attempts()),
                          default=0.0)
                      for t in self.maps if t.state == "running")
        return min(1.0, (self.finished_maps + running) / len(self.maps))

    def reduce_progress(self) -> float:
        if not self.reduces:
            return 1.0
        return self.finished_reduces / len(self.reduces)

    # ------------------------------------------- scheduling feedback model

    def devcache_tags(self) -> "tuple[str, ...]":
        """Side-input devcache tags this job's device tasks stage
        (``tpumr.devcache.required.tags``, or derived from the kernels'
        known side-input confs) — the affinity scheduler matches these
        against tracker-piggybacked inventories. The derivation is
        string-level coupling with ops/kmeans.device_centroids and
        ops/matmul: the tag IS ``family:path``, so the conf that names
        the side input names the tag."""
        v = self._devcache_tags
        if v is None:
            explicit = str(confkeys.get(
                self.conf, "tpumr.devcache.required.tags") or "")
            tags = [t.strip() for t in explicit.split(",") if t.strip()]
            if not tags:
                c = self.conf.get("tpumr.kmeans.centroids")
                if c:
                    tags.append(f"kmeans-centroids:{c}")
                b = self.conf.get("tpumr.matmul.b")
                if b:
                    tags.append(f"matmul-b:{b}")
            v = self._devcache_tags = tuple(tags)
        return v

    def speculative_in_flight(self) -> int:
        """Speculative attempts launched and not yet terminal — the
        scheduler gauge's per-job term. Lock-free: len() of a set only
        mutated under the job lock; one beat of staleness is fine."""
        return len(self._spec_attempts)

    def _fold_progress(self, tip: TaskInProgress,
                       status: TaskStatus) -> None:
        """Fold one RUNNING status into the TIP's progress-rate EWMA.
        Master-local monotonic stamps only — the status' own wall
        clocks never enter the math (cross-host skew). A beat with no
        progress advance leaves the anchor alone, so the next advance
        averages over the whole stall. Caller holds ``self.lock``."""
        now = time.monotonic()
        if tip.dispatch_mono == 0.0:
            tip.dispatch_mono = now   # adopted/recovered attempt
        p = min(1.0, max(0.0, status.progress))
        if p <= tip.last_progress:
            return
        base = tip.last_progress_mono or tip.dispatch_mono
        dt = now - base
        if dt <= 0.0:
            return
        rate = (p - tip.last_progress) / dt
        a = self._rate_alpha
        tip.rate_ewma = rate if not tip.rate_ewma \
            else a * rate + (1 - a) * tip.rate_ewma
        tip.last_progress = p
        tip.last_progress_mono = now

    @staticmethod
    def _tip_remaining_s(tip: TaskInProgress, now: float,
                         mean_hint: float) -> float:
        """Estimated seconds until a RUNNING tip finishes: rate EWMA
        when it reports progress; elapsed-proportional fallback before
        the first EWMA fold; a full mean runtime when it has shown no
        progress at all — a silent tip must look LONG, never
        nearly-done (stalls are exactly what speculation targets)."""
        p = tip.last_progress
        if tip.rate_ewma > 0.0:
            return max(0.0, (1.0 - p) / tip.rate_ewma)
        elapsed = now - (tip.dispatch_mono or now)
        if p > 0.0 and elapsed > 0.0:
            return elapsed * (1.0 - p) / p
        return max(0.0, mean_hint)

    def _remaining_locked(self, tips: "list[TaskInProgress]", now: float,
                          mean_hint: float) -> "dict[int, float]":
        return {t.partition: self._tip_remaining_s(t, now, mean_hint)
                for t in tips if t.state == "running"}

    def _map_mean_locked(self) -> float:
        done = self.finished_cpu_maps + self.finished_tpu_maps
        return ((self._cpu_time_sum + self._tpu_time_sum) / done) \
            if done else 0.0

    def map_remaining_estimates(self) -> "dict[int, float]":
        """partition → estimated seconds remaining, for RUNNING maps."""
        with self.lock:
            return self._remaining_locked(self.maps, time.monotonic(),
                                          self._map_mean_locked())

    def critical_path_maps(self) -> "set[int]":
        """Running map partitions on the estimated critical path: those
        whose remaining estimate is within
        ``tpumr.speculative.critical.fraction`` of the longest."""
        est = self.map_remaining_estimates()
        if not est:
            return set()
        mx = max(est.values())
        if mx <= 0.0:
            return set(est)
        return {p for p, r in est.items()
                if r >= self._spec_cp_fraction * mx}

    def longest_remaining_path_s(self) -> float:
        """Live longest-remaining-path estimate: the slowest running
        map's remaining (pending maps contribute at least one mean
        runtime — they haven't even started) plus the same term for the
        reduce phase. An estimate of the floor on job completion, not a
        promise; the targeted speculation pass and the /job page read
        it."""
        with self.lock:
            now = time.monotonic()
            m_mean = self._map_mean_locked()
            m_est = self._remaining_locked(self.maps, now, m_mean)
            path = max(m_est.values(), default=0.0)
            if self._pending_maps:
                path = max(path, m_mean)
            r_mean = self._reduce_time_sum / self.finished_reduces \
                if self.finished_reduces else 0.0
            r_est = self._remaining_locked(self.reduces, now, r_mean)
            rpath = max(r_est.values(), default=0.0)
            if self._pending_reduces:
                rpath = max(rpath, r_mean)
            return path + rpath

    def _note_spec_launch(self, attempt: TaskAttemptID) -> None:
        """Account one speculative twin launch (caller holds the lock)."""
        self.speculative_launched += 1
        self._spec_attempts.add(str(attempt))

    def _settle_speculative(self, aid: str, won: bool) -> None:
        """A speculative attempt reached a terminal state: move it from
        in-flight to won/wasted. No-op for non-speculative attempts.
        Caller holds ``self.lock``."""
        if aid in self._spec_attempts:
            self._spec_attempts.discard(aid)
            if won:
                self.speculative_won += 1
            else:
                self.speculative_wasted += 1

    # ------------------------------------------------------------ obtain

    def obtain_new_map_task(self, host: str, run_on_tpu: bool,
                            tpu_device_id: int = -1,
                            rack: "str | None" = None) -> Task | None:
        """Locality-preferring map assignment ≈ obtainNewNodeLocalMapTask →
        obtainNewNonLocalMapTask (selection path of
        JobQueueTaskScheduler.java:306-317)."""
        with self.lock:
            if self.state != JobState.RUNNING:
                return None
            if self.schedule_hold_until \
                    and time.monotonic() < self.schedule_hold_until:
                return None  # recovery grace: re-joining trackers first
            if run_on_tpu and self.tpu_disabled:
                return None  # job-level accelerator quarantine
            # demoted TIPs never land on TPU again; the CPU pass sees all
            eligible = (self._pending_maps - self._cpu_only_maps
                        if run_on_tpu else self._pending_maps)
            if not self._pending_maps:
                return self._obtain_speculative_map(host, run_on_tpu,
                                                    tpu_device_id)
            if not eligible:
                return None  # pending work exists but none TPU-eligible
            # tiers: node-local → rack-local → any (≈ obtainNewNodeLocal /
            # rack-local / NonLocal MapTask). The tracker reports its own
            # rack (resolved tracker-side); resolving here is the fallback
            # for local/direct callers only — it may exec the topology
            # script, which must not happen on the scheduling path.
            local = self.host_cache.get(host, set()) & eligible
            if not local:
                if rack is None:
                    rack = self._rack_resolver(host)
                if rack != self._default_rack:
                    local = self.rack_cache.get(rack, set()) & eligible
            idx = min(local) if local else min(eligible)
            self._pending_maps.discard(idx)
            tip = self.maps[idx]
            tip.state = "running"
            tip.dispatch_mono = tip.dispatch_mono or time.monotonic()
            self._record_placement(run_on_tpu)
            attempt = tip.new_attempt()
            tip.report.state = TaskState.RUNNING
            tip.report.start_time = tip.report.start_time or time.time()
            # stamp placement on the report ≈ JobTracker.java:3414-3433
            tip.report.run_on_tpu = run_on_tpu
            tip.report.tpu_device_id = tpu_device_id
            return Task(attempt, partition=idx, num_reduces=self.num_reduces,
                        split=tip.split, num_maps=len(self.maps),
                        run_on_tpu=run_on_tpu, tpu_device_id=tpu_device_id,
                        memory_mb=self.map_memory_mb())

    def _obtain_speculative_map(self, host: str, run_on_tpu: bool,
                                tpu_device_id: int) -> Task | None:
        """Straggler mitigation ≈ JobInProgress.hasSpeculativeMap /
        speculativeMapTasks (JobInProgress.java:2777): when all maps are
        assigned but some lag, issue a duplicate attempt; first
        completion wins (the loser is killed by the master).

        Two modes. Blanket (``tpumr.speculative.targeted=false``): the
        reference's age-only rule — any running TIP older than
        max(floor, factor·mean) twins. Targeted (default), LATE-style:
        a TIP is speculated only when its ESTIMATED FINISH (elapsed +
        estimated remaining, from the per-TIP progress-rate EWMA) lags
        the job's completed-runtime distribution AND it sits on the
        estimated critical path, under a concurrent-speculation cap.
        Caller holds self.lock."""
        if not self.speculative or self.speculation_hold:
            return None
        if run_on_tpu and self.tpu_disabled:
            return None
        # denominator matches the sums: a TPU quarantine unwinds both
        # finished_tpu_maps and _tpu_time_sum, so using finished_maps
        # here would deflate the mean and over-speculate exactly when
        # the job just lost its accelerator capacity
        done = self.finished_cpu_maps + self.finished_tpu_maps
        if done == 0:
            return None
        mean = ((self._cpu_time_sum + self._tpu_time_sum) / done)
        factor = confkeys.get_float(
            self.conf, "mapred.speculative.lag.factor")
        # minimum runtime before a task can be speculated — ≈ the
        # reference's SPECULATIVE_LAG (60s); without a floor, short-task
        # jobs speculate everything instantly
        floor = confkeys.get_float(
            self.conf, "mapred.speculative.min.runtime.s")
        targeted = self.speculative_targeted
        if targeted and len(self._spec_attempts) >= self.speculative_cap:
            return None  # concurrent-speculation cap
        now = time.monotonic()
        est: "dict[int, float]" = {}
        max_rem = 0.0
        if targeted:
            est = self._remaining_locked(self.maps, now, mean)
            max_rem = max(est.values(), default=0.0)
        for tip in self.maps:
            if tip.state != "running":
                continue
            if tip.next_attempt != 1:
                continue  # already speculated (or restarted) — one dup max
            if run_on_tpu and tip.partition in self._cpu_only_maps:
                continue  # a demoted TIP's twin must not land on TPU
            # master-local monotonic age: the dispatch stamp lives in the
            # same clock domain as ``now``, so no wall arithmetic here
            elapsed = now - (tip.dispatch_mono or now)
            if targeted:
                if elapsed <= floor:
                    continue
                remaining = est.get(tip.partition, 0.0)
                if elapsed + remaining <= factor * mean:
                    continue  # estimated finish within the distribution
                if max_rem > 0.0 \
                        and remaining < self._spec_cp_fraction * max_rem:
                    continue  # lagging, but not on the critical path
            elif elapsed <= max(floor, factor * mean):
                continue
            attempt = tip.new_attempt()
            self.speculative_map_tasks += 1
            self._note_spec_launch(attempt)
            self._record_placement(run_on_tpu)
            tip.report.run_on_tpu = run_on_tpu
            tip.report.tpu_device_id = tpu_device_id
            return Task(attempt, partition=tip.partition,
                        num_reduces=self.num_reduces, split=tip.split,
                        num_maps=len(self.maps), run_on_tpu=run_on_tpu,
                        tpu_device_id=tpu_device_id,
                        memory_mb=self.map_memory_mb())
        return None

    def should_kill_attempt(self, attempt_id: str) -> bool:
        """True when this RUNNING attempt lost a speculative race — its TIP
        already succeeded through a different attempt (≈ the reference
        killing the slower speculative twin) — or a scheduler marked it for
        preemption (≈ FairScheduler.preemptTasksIfNecessary)."""
        from tpumr.mapred.ids import TaskAttemptID
        with self.lock:
            if attempt_id in self._preempt_requested:
                return True
            tip = self._tip_of(TaskAttemptID.parse(attempt_id).task)
            return (tip is not None and tip.state == "succeeded"
                    and tip.successful_attempt != attempt_id)

    def kill_marked(self, attempt_id: str) -> bool:
        """Lock-free kill-scan probe (see ``_kill_marked``); a mark set
        mid-probe is caught on the next beat."""
        return attempt_id in self._kill_marked

    def request_preempt(self, attempt_id: str) -> None:
        """Mark a RUNNING attempt for preemption: the next heartbeat of its
        tracker carries a kill action; the KILLED report requeues the TIP
        without counting a failure (fair-scheduler min-share restoration —
        the reference kills tasks of over-share pools the same way)."""
        with self.lock:
            self._preempt_requested.add(attempt_id)
            self._kill_marked.add(attempt_id)

    def request_attempt_kill(self, attempt_id: str,
                             fail: bool = False) -> bool:
        """Operator-driven attempt kill ≈ JobTracker.killTask(taskid,
        shouldFail) — `job -kill-task` / `-fail-task`. ``fail=True``
        makes the attempt count toward the task's attempt limit (the
        -fail-task semantics); plain kill re-queues without burning an
        attempt. Returns False when the attempt is unknown or already
        terminal."""
        with self.lock:
            tip = self._tip_of_attempt(attempt_id)
            if tip is None:
                return False
            st = tip.attempts.get(attempt_id)
            if st is None or st.state in TaskState.TERMINAL:
                # unknown to the master, or already finished — nothing
                # to kill (the reference's killTask returns false too)
                return False
            self._preempt_requested.add(attempt_id)
            self._kill_marked.add(attempt_id)
            if fail:
                self._fail_requested.add(attempt_id)
            return True

    def _tip_of_attempt(self, attempt_id: str) -> "TaskInProgress | None":
        from tpumr.mapred.ids import TaskAttemptID
        try:
            return self._tip_of(TaskAttemptID.parse(attempt_id).task)
        except (ValueError, KeyError, IndexError):
            return None

    def preempt_pending(self) -> set[str]:
        """Attempts marked but not yet observed terminal (so the scheduler
        does not double-count in-flight preemptions when sizing the next
        round of kills)."""
        with self.lock:
            return set(self._preempt_requested)

    def running_map_attempts(self) -> "list[tuple[str, float]]":
        """(attempt_id, start_time) for every RUNNING map attempt — the
        fair scheduler's victim candidates (newest first is the caller's
        sort)."""
        with self.lock:
            out = []
            for tip in self.maps:
                for aid, st in tip.attempts.items():
                    if st.state == TaskState.RUNNING:
                        out.append((aid, st.start_time))
            return out

    def map_memory_mb(self) -> int:
        """Declared per-map memory demand (mapred.job.map.memory.mb, 0 =
        undeclared) — the capacity scheduler's memory-matching input
        (≈ CapacityTaskScheduler's memory checks)."""
        return confkeys.get_int(self.conf, "mapred.job.map.memory.mb")

    def reduce_memory_mb(self) -> int:
        return confkeys.get_int(self.conf,
                                "mapred.job.reduce.memory.mb")

    def obtain_new_reduce_task(self, host: str) -> Task | None:
        with self.lock:
            if self.state != JobState.RUNNING:
                return None
            if self.schedule_hold_until \
                    and time.monotonic() < self.schedule_hold_until:
                return None  # recovery grace: re-joining trackers first
            if not self._pending_reduces:
                return self._obtain_speculative_reduce()
            # slowstart gate ≈ JobInProgress.scheduleReduces
            if self.finished_maps < self.slowstart * max(1, len(self.maps)):
                return None
            idx = min(self._pending_reduces)
            self._pending_reduces.discard(idx)
            tip = self.reduces[idx]
            tip.state = "running"
            tip.dispatch_mono = tip.dispatch_mono or time.monotonic()
            attempt = tip.new_attempt()
            tip.report.state = TaskState.RUNNING
            tip.report.start_time = tip.report.start_time or time.time()
            return Task(attempt, partition=idx, num_reduces=self.num_reduces,
                        num_maps=len(self.maps),
                        memory_mb=self.reduce_memory_mb())

    def _obtain_speculative_reduce(self) -> Task | None:
        """Straggler mitigation for the phase that ends every job ≈
        JobInProgress.hasSpeculativeReduces / findSpeculativeTask
        (JobInProgress.java:257,739,2320): when all reduces are assigned
        but one runs much longer than the completed mean, issue a
        duplicate attempt; first completion wins (the loser is killed by
        the master via should_kill_attempt, and the output committer's
        promote-on-commit makes the race safe). Same progress-gap rule
        as maps (and the same targeted/blanket split as the map pass).
        Caller holds ``self.lock``."""
        if not self.speculative_reduces or self.speculation_hold \
                or self.finished_reduces == 0:
            return None
        mean = self._reduce_time_sum / self.finished_reduces
        factor = confkeys.get_float(
            self.conf, "mapred.speculative.lag.factor")
        floor = confkeys.get_float(
            self.conf, "mapred.speculative.min.runtime.s")
        targeted = self.speculative_targeted
        if targeted and len(self._spec_attempts) >= self.speculative_cap:
            return None  # concurrent-speculation cap (shared with maps)
        now = time.monotonic()
        est: "dict[int, float]" = {}
        max_rem = 0.0
        if targeted:
            est = self._remaining_locked(self.reduces, now, mean)
            max_rem = max(est.values(), default=0.0)
        for tip in self.reduces:
            if tip.state != "running":
                continue
            if tip.next_attempt != 1:
                continue  # already speculated (or restarted) — one dup max
            # master-local monotonic age, as in the map pass above
            elapsed = now - (tip.dispatch_mono or now)
            if targeted:
                if elapsed <= floor:
                    continue
                remaining = est.get(tip.partition, 0.0)
                if elapsed + remaining <= factor * mean:
                    continue
                if max_rem > 0.0 \
                        and remaining < self._spec_cp_fraction * max_rem:
                    continue
            elif elapsed <= max(floor, factor * mean):
                continue
            attempt = tip.new_attempt()
            self.speculative_reduce_tasks += 1
            self._note_spec_launch(attempt)
            return Task(attempt, partition=tip.partition,
                        num_reduces=self.num_reduces,
                        num_maps=len(self.maps),
                        memory_mb=self.reduce_memory_mb())
        return None

    # ------------------------------------------------------------ updates

    def update_task_status(self, status: TaskStatus,
                           tracker_shuffle_addr: str = "") -> None:
        with self.lock:
            tip = self._tip_of(status.attempt_id.task)
            if tip is None:
                return
            aid_s = str(status.attempt_id)
            prev = tip.attempts.get(aid_s)
            if prev is not None and prev.state in (TaskState.FAILED,
                                                   TaskState.KILLED):
                # the master already terminally settled this attempt
                # (withdrawn output, lost tracker, -fail-task): a
                # replayed tracker status must neither resurrect a dead
                # attempt (a re-delivered SUCCEEDED would re-publish a
                # withdrawn shuffle address and re-increment
                # finished_maps while the tip sits in _pending_maps) nor
                # double-count its failure
                return
            if status.state in TaskState.TERMINAL:
                self._preempt_requested.discard(aid_s)
                self._kill_marked.discard(aid_s)
                if status.state == TaskState.KILLED \
                        and aid_s in self._fail_requested:
                    # -fail-task: the tracker reports the kill as KILLED;
                    # the operator asked for FAILED semantics (burn an
                    # attempt) — rewrite before accounting
                    status = replace(status, state=TaskState.FAILED,
                                     diagnostics=(status.diagnostics
                                                  or "failed by operator "
                                                     "(-fail-task)"))
                # any terminal outcome clears the fail mark (an attempt
                # that FAILED or SUCCEEDED on its own must not leak a
                # stale entry for the life of the job)
                self._fail_requested.discard(aid_s)
            tip.attempts[str(status.attempt_id)] = status
            tip.report.progress = max(tip.report.progress, status.progress)
            if status.state == TaskState.RUNNING \
                    and tip.state == "running":
                # the feedback model's input: per-TIP progress-rate EWMA
                # folded here, under the job lock only (off the
                # heartbeat fast path per the PR-8 lock ranks)
                self._fold_progress(tip, status)
            if status.state == TaskState.RUNNING \
                    and tip.state == "succeeded" \
                    and tip.successful_attempt != aid_s:
                # a speculative loser reporting progress after its twin
                # already won (possibly its FIRST report): mark it so
                # the kill scan catches it without re-deriving the race
                self._kill_marked.add(aid_s)
            if status.state == TaskState.SUCCEEDED:
                self._on_success(tip, status, tracker_shuffle_addr)
            elif status.state in (TaskState.FAILED, TaskState.KILLED):
                self._on_failure(tip, status)

    def _tip_of(self, task_id: TaskID) -> TaskInProgress | None:
        arr = self.maps if task_id.is_map else self.reduces
        return arr[task_id.id] if task_id.id < len(arr) else None

    def _on_success(self, tip: TaskInProgress, status: TaskStatus,
                    shuffle_addr: str) -> None:
        aid = str(status.attempt_id)
        if tip.state == "succeeded":
            # a speculative duplicate — first completion wins (and this
            # late finisher's work is by definition wasted)
            self._settle_speculative(aid, won=False)
            return
        tip.state = "succeeded"
        tip.successful_attempt = aid
        self._settle_speculative(aid, won=True)
        # the losing speculative twins (any other attempt still RUNNING)
        # get their kill marks NOW — the heartbeat kill scan reads the
        # mark set lock-free instead of re-deriving the race per beat
        for other_aid, other in tip.attempts.items():
            if other_aid != tip.successful_attempt \
                    and other.state == TaskState.RUNNING:
                self._kill_marked.add(other_aid)
        tip.report.state = TaskState.SUCCEEDED
        tip.report.progress = 1.0
        tip.report.finish_time = status.finish_time or time.time()
        tip.report.successful_attempt = str(status.attempt_id)
        if status.counters:
            self.counters.merge(Counters.from_dict(status.counters))
        # a completion may fold for a tip the master believed PENDING: a
        # restarted master recovers in-flight tasks as pending, and the
        # re-joining tracker's first beat can carry the attempt's
        # (undelivered) terminal status directly — the tip must leave
        # the pending set or the scheduler re-assigns finished work
        if tip.is_map:
            self._pending_maps.discard(tip.partition)
        else:
            self._pending_reduces.discard(tip.partition)
        if tip.is_map:
            self.finished_maps += 1
            runtime = status.runtime
            self._record_runtime(runtime, is_map=True,
                                 on_tpu=bool(status.run_on_tpu))
            if status.run_on_tpu:
                # post-quarantine TPU completions (in-flight attempts
                # finishing after tpu_disabled) are excluded from BOTH
                # backends' profiles: the unwound TPU sums must not
                # resurrect, and folding TPU runtimes into the CPU mean
                # would skew it just as badly
                if not self.tpu_disabled:
                    self.finished_tpu_maps += 1
                    self._tpu_time_sum += runtime
                    if self._ewma_alpha:
                        a = self._ewma_alpha
                        self._tpu_ewma = (
                            runtime if not self._tpu_ewma
                            else a * runtime + (1 - a) * self._tpu_ewma)
            else:
                self.finished_cpu_maps += 1
                self._cpu_time_sum += runtime
                if self._ewma_alpha:
                    a = self._ewma_alpha
                    self._cpu_ewma = (runtime if not self._cpu_ewma
                                      else a * runtime + (1 - a) * self._cpu_ewma)
            self.completion_events.append({
                "map_index": tip.partition,
                "attempt_id": str(status.attempt_id),
                "shuffle_addr": shuffle_addr,
                "status": "SUCCEEDED",
                # tracker-stamped map-output size: reducers order their
                # fetch queues largest-first on it (size-aware shuffle)
                "output_bytes": int(getattr(status, "output_bytes", 0)
                                    or 0),
            })
        else:
            self.finished_reduces += 1
            self._reduce_time_sum += status.runtime
            self._record_runtime(status.runtime, is_map=False)
            if self.stream_handoff:
                # announce the committed reduce partition to downstream
                # pipeline stages (their HandoffSplit readers poll this
                # feed through the same MapLocator the shuffle uses)
                self.handoff_events.append({
                    "map_index": tip.partition,
                    "attempt_id": str(status.attempt_id),
                    "shuffle_addr": shuffle_addr,
                    "status": "SUCCEEDED",
                })
        if (self.finished_maps == len(self.maps)
                and self.finished_reduces == len(self.reduces)):
            self.state = JobState.SUCCEEDED
            self.finish_time = time.time()

    _MAX_RUNTIME_SAMPLES = 65536

    def _record_runtime(self, runtime: float, is_map: bool,
                        on_tpu: bool = False) -> None:
        """Keep one successful attempt's runtime for the stats rollup
        (caller holds ``self.lock`` via update_task_status)."""
        if len(self.map_runtimes) + len(self.reduce_runtimes) \
                >= self._MAX_RUNTIME_SAMPLES:
            self.runtimes_dropped += 1
            return
        if is_map:
            self.map_runtimes.append((float(runtime), on_tpu))
        else:
            self.reduce_runtimes.append(float(runtime))

    def _on_failure(self, tip: TaskInProgress, status: TaskStatus) -> None:
        # a FAILED/KILLED speculative twin settles as wasted whether or
        # not its TIP already succeeded through the other attempt
        self._settle_speculative(str(status.attempt_id), won=False)
        if tip.state == "succeeded":
            return
        if status.state == TaskState.FAILED:
            # KILLED attempts (lost trackers, job kills, lost commit races)
            # do NOT count toward the attempt limit — only real failures do
            # (Hadoop excludes killed attempts the same way)
            tip.failures += 1
            from tpumr.mapred.task import FailureClass
            if (tip.is_map and status.run_on_tpu
                    and status.failure_class in FailureClass.ACCELERATOR):
                self._note_tpu_failure(tip, status)
        limit = self.max_map_attempts if tip.is_map else self.max_reduce_attempts
        if status.state == TaskState.FAILED and tip.failures >= limit:
            self.state = JobState.FAILED
            self.finish_time = time.time()
            self.error = (f"task {tip.task_id} failed {tip.failures} times; "
                          f"last: {status.diagnostics}")
            return
        # if a twin attempt (speculative, or not-yet-reaped) is still
        # running, don't re-queue — a third concurrent attempt would waste
        # a slot and the live twin may still succeed
        aid = str(status.attempt_id)
        if any(s.state == TaskState.RUNNING and str(s.attempt_id) != aid
               for s in tip.attempts.values()):
            tip.state = "running"
            return
        # re-queue (≈ lost/failed task re-execution)
        tip.state = "pending"
        tip.reset_feedback()
        if tip.is_map:
            self._pending_maps.add(tip.partition)
        else:
            self._pending_reduces.add(tip.partition)

    def _note_tpu_failure(self, tip: TaskInProgress,
                          status: TaskStatus) -> None:
        """One device/compile-classed TPU failure: walk the TIP toward
        CPU-only pinning and the job toward TPU quarantine. Caller holds
        ``self.lock`` (via update_task_status)."""
        from tpumr.core.counters import JobCounter
        tip.tpu_failures += 1
        self._tpu_failed_tips.add(tip.partition)
        if (tip.partition not in self._cpu_only_maps
                and tip.tpu_failures >= self.tpu_attempt_retries):
            # ≈ the reference re-landing a deterministically-crashing
            # kernel on the same backend until the job dies — instead
            # the TIP's remaining attempts are pinned to the CPU pass
            self._cpu_only_maps.add(tip.partition)
            self.counters.incr(JobCounter.GROUP, JobCounter.TPU_DEMOTIONS)
            self._accel_events.append({
                "kind": "tip_demoted", "task_id": str(tip.task_id),
                "attempt_id": str(status.attempt_id),
                "failure_class": status.failure_class,
                "tpu_failures": tip.tpu_failures})
        if (not self.tpu_disabled
                and len(self._tpu_failed_tips) >= self.tpu_quarantine_tips):
            # enough DISTINCT tasks indicted the accelerator path: the
            # fault is the job's kernel (or the fleet's devices), not
            # one unlucky split — stop offering this job TPU work at all
            self.tpu_disabled = True
            # unwind the TPU profile sums so acceleration_factor → 1.0:
            # a poisoned factor would keep the optional-scheduling gate
            # starving the CPU pass, deadlocking the job it just demoted
            self.finished_tpu_maps = 0
            self._tpu_time_sum = 0.0
            self._tpu_ewma = 0.0
            self._accel_events.append({
                "kind": "job_tpu_quarantined",
                "failed_tips": len(self._tpu_failed_tips),
                "attempt_id": str(status.attempt_id)})

    def _obsolete_map_output(self, tip: TaskInProgress, aid: str) -> str:
        """Withdraw a published map output: mark its completion event(s)
        OBSOLETE in place (late consumers replaying from cursor 0 see
        SUCCEEDED→OBSOLETE in order) AND append a tombstone event so
        consumers whose cursor is already past the original learn of the
        withdrawal. Returns the shuffle address that served the output
        ("" when it was never published). Caller holds ``self.lock``."""
        addr = ""
        for e in self.completion_events:
            if e["attempt_id"] == aid and e.get("status") != "OBSOLETE":
                addr = e.get("shuffle_addr", "")
                e["status"] = "OBSOLETE"
        self.completion_events.append({
            "map_index": tip.partition, "attempt_id": aid,
            "shuffle_addr": addr, "status": "OBSOLETE"})
        return addr

    def _unwind_finished_map(self, tip: TaskInProgress,
                             st: "TaskStatus | None") -> None:
        """Take one completed map back out of the books: completion
        count AND the per-backend profile sums, so the hybrid
        scheduler's means aren't poisoned by a re-run being
        double-counted. Caller holds ``self.lock``."""
        self.finished_maps -= 1
        if st is not None and st.is_map:
            if st.run_on_tpu:
                self.finished_tpu_maps -= 1
                self._tpu_time_sum -= st.runtime
            else:
                self.finished_cpu_maps -= 1
                self._cpu_time_sum -= st.runtime

    def fetch_failure_notification(self, map_attempt: str,
                                   reduce_attempt: str) -> "dict | None":
        """A reducer reports ``map_attempt``'s output unfetchable
        (≈ JobInProgress.fetchFailureNotification, reached via
        ReduceTask's umbilical → heartbeat). Distinct reporting reducers
        are counted per map attempt; at ``mapred.max.fetch.failures.per.
        map`` (or once EVERY live reduce is reporting — a 1-reduce job
        could never reach 3) the still-"successful" attempt is failed:
        its output is withdrawn (OBSOLETE completion events), the hybrid
        profile sums are unwound, and the map re-queues for re-execution
        while the reporting reduces stay alive in their penalty-box
        retry loops. Returns None for stale/unknown reports, else a dict
        with ``reexecuted`` and the serving ``shuffle_addr`` (so the
        master can charge a fault to the lame tracker)."""
        try:
            attempt = TaskAttemptID.parse(map_attempt)
            reducer = TaskAttemptID.parse(reduce_attempt)
        except (ValueError, IndexError):
            return None
        with self.lock:
            if self.state != JobState.RUNNING or not attempt.task.is_map:
                return None
            tip = self._tip_of(attempt.task)
            if tip is None:
                return None
            # the reporter must be a real, running reduce attempt of
            # THIS job (≈ the reference trusting only its own umbilical
            # children): forged reducer names must not be able to
            # manufacture "distinct reducers" and kill healthy maps.
            # Attempts adopted from the job this one was recovered from
            # (master restart) carry the OLD job id and count as ours.
            if reducer.task.is_map or (
                    reducer.task.job != self.job_id
                    and str(reducer.task.job) != (self.recovered_from
                                                  or "")):
                return None
            rtip = self._tip_of(reducer.task)
            rst = rtip.attempts.get(reduce_attempt) \
                if rtip is not None else None
            if rst is None or rst.state != TaskState.RUNNING:
                return None
            if tip.state != "succeeded" \
                    or tip.successful_attempt != map_attempt:
                # stale: the output was already withdrawn (lost tracker
                # or an earlier notification) — the reducer just hasn't
                # refreshed its events yet
                return None
            reporters = self._fetch_failures.setdefault(map_attempt, set())
            # keyed by reduce TASK, not attempt: a speculative twin is
            # the same reducer corroborating nothing new
            reporters.add(str(reducer.task))
            n_reports = len(reporters)
            live_reduces = max(1, len(self.reduces) - self.finished_reduces)
            threshold = min(self.max_fetch_failures_per_map, live_reduces)
            if n_reports < threshold:
                return {"withdrawn": False, "reexecuted": False,
                        "shuffle_addr": "", "reports": n_reports}
            del self._fetch_failures[map_attempt]
            addr = self._obsolete_map_output(tip, map_attempt)
            st = tip.attempts.get(map_attempt)
            if st is not None:
                st.state = TaskState.FAILED
                st.diagnostics = (
                    f"Too many fetch failures: {n_reports} reducer(s) "
                    f"could not fetch this attempt's output from {addr}")
            # the attempt is burned (≈ failedTask for fetch failures): a
            # map whose output keeps vanishing eventually fails the job
            # like any other repeatedly-failing task
            tip.failures += 1
            tip.state = "pending"
            tip.successful_attempt = ""
            tip.reset_feedback()
            self._unwind_finished_map(tip, st)
            self._pending_maps.add(tip.partition)
            if tip.failures >= self.max_map_attempts:
                self.state = JobState.FAILED
                self.finish_time = time.time()
                self.error = (f"map {tip.task_id} lost its output to "
                              f"fetch failures {tip.failures} times")
                return {"withdrawn": True, "reexecuted": False,
                        "shuffle_addr": addr, "reports": n_reports}
            return {"withdrawn": True, "reexecuted": True,
                    "shuffle_addr": addr, "reports": n_reports}

    def fetch_failure_pending_count(self) -> int:
        """Map attempts with outstanding (sub-threshold) fetch-failure
        reports — the master's penalty-ledger gauge."""
        with self.lock:
            return len(self._fetch_failures)

    def requeue_lost_attempts(self, attempt_ids: list[str]) -> "list[str]":
        """Tracker lost (≈ JobTracker.lostTaskTracker): running attempts on
        it are killed and their tasks re-queued; completed MAPS are also
        re-queued because their outputs lived on the lost tracker — unless
        the job has no reduces (reference semantics). Returns the attempt
        ids whose published map outputs were withdrawn, so the caller can
        journal MAP_OUTPUT_LOST events (restart recovery must not adopt
        outputs the master already declared gone)."""
        withdrawn: "list[str]" = []
        with self.lock:
            for aid in attempt_ids:
                attempt = TaskAttemptID.parse(aid)
                tip = self._tip_of(attempt.task)
                if tip is None:
                    continue
                # a lost attempt is terminal either way — a pending preempt
                # mark must not linger as a phantom in-flight kill
                self._preempt_requested.discard(aid)
                self._kill_marked.discard(aid)
                st = tip.attempts.get(aid)
                if st is not None and st.state == TaskState.RUNNING:
                    # honor a pending -fail-task even when the tracker
                    # died before delivering the kill: the operator asked
                    # for a burned attempt, not a free requeue
                    if aid in self._fail_requested:
                        st.state = TaskState.FAILED
                        st.diagnostics = (st.diagnostics or
                                          "failed by operator (-fail-task)")
                    else:
                        st.state = TaskState.KILLED
                    self._on_failure(tip, st)
                elif (tip.is_map and tip.state == "succeeded"
                      and tip.successful_attempt == aid
                      and self.num_reduces > 0
                      and self.state == JobState.RUNNING):
                    tip.state = "pending"
                    tip.successful_attempt = ""
                    tip.reset_feedback()
                    # unwind the backend profile so the re-run isn't
                    # double-counted in the hybrid scheduler's means
                    self._unwind_finished_map(tip, st)
                    self._pending_maps.add(tip.partition)
                    self._obsolete_map_output(tip, aid)
                    self._fetch_failures.pop(aid, None)
                    withdrawn.append(aid)
                # lost = terminal for this attempt whatever branch ran:
                # never leak a -fail-task mark for the life of the job
                self._fail_requested.discard(aid)
        return withdrawn

    def withdraw_handoff_at(self, addr: str) -> int:
        """The tracker serving streamed-handoff reduce output at
        ``addr`` is gone: tombstone its announcements (OBSOLETE in
        place + appended, the PR-1 withdrawal dialect) so downstream
        readers evict the location and fall back to the COMMITTED part
        file — the reduce itself never re-runs for this (its DFS output
        survived the tracker). Runs for terminal jobs too: a finished
        upstream stage keeps serving a live pipeline. Returns the
        number of partitions withdrawn."""
        if not self.stream_handoff:
            return 0
        with self.lock:
            # snapshot before appending tombstones: the feed grows
            # under this very loop otherwise
            live = [e for e in self.handoff_events
                    if e.get("shuffle_addr") == addr
                    and e.get("status") != "OBSOLETE"]
            for e in live:
                e["status"] = "OBSOLETE"
                self.handoff_events.append({
                    "map_index": e["map_index"],
                    "attempt_id": e["attempt_id"],
                    "shuffle_addr": addr, "status": "OBSOLETE"})
        return len(live)

    # ------------------------------------------------------------ recovery

    def recover_attempts(self, state: dict, old_job_id: str) -> int:
        """Replay an interrupted job's completed attempts (from
        ``JobHistory.recovered_attempt_state``) into this resubmitted
        job: completed maps are marked SUCCEEDED with their ORIGINAL
        attempt ids and their completion events re-fed into the
        append-only feed (reducers fetch the surviving outputs instead
        of waiting for re-runs); completed reduces are simply counted
        done (their output is already committed). A recovered output
        that turns out to be gone re-executes through the normal
        fetch-failure protocol. Returns the number of attempts adopted
        from history."""
        n = 0
        with self.lock:
            self.recovered_from = old_job_id
            for idx, rec in sorted((state.get("maps") or {}).items()):
                idx = int(idx)
                if idx >= len(self.maps):
                    continue
                if self.num_reduces > 0 and not rec.get("shuffle_addr"):
                    # no recorded serving address (pre-upgrade history):
                    # reducers could never fetch it — re-run instead
                    continue
                self._recover_one(self.maps[idx], rec)
                if self.num_reduces > 0:
                    self.completion_events.append({
                        "map_index": idx,
                        "attempt_id": rec["attempt_id"],
                        "shuffle_addr": rec["shuffle_addr"],
                        "status": "SUCCEEDED",
                    })
                n += 1
            for idx, rec in sorted((state.get("reduces") or {}).items()):
                idx = int(idx)
                if idx >= len(self.reduces):
                    continue
                self._recover_one(self.reduces[idx], rec)
                if self.stream_handoff and rec.get("shuffle_addr"):
                    # re-announce the surviving streamed handoff copy:
                    # downstream readers' cursors rewind on the shorter
                    # post-restart feed (MapLocator's starvation rewind)
                    # and re-fold idempotently
                    self.handoff_events.append({
                        "map_index": idx,
                        "attempt_id": rec["attempt_id"],
                        "shuffle_addr": rec["shuffle_addr"],
                        "status": "SUCCEEDED",
                    })
                n += 1
            if (self.finished_maps == len(self.maps)
                    and self.finished_reduces == len(self.reduces)):
                # the crash fell between the last completion and
                # finalization — the caller finalizes, nothing re-runs
                self.state = JobState.SUCCEEDED
                self.finish_time = time.time()
        return n

    def _recover_one(self, tip: TaskInProgress, rec: dict) -> None:
        """Adopt one history-recovered successful attempt into its TIP.
        Caller holds ``self.lock``."""
        aid = rec["attempt_id"]
        finish = rec.get("ts") or time.time()
        runtime = float(rec.get("runtime", 0.0) or 0.0)
        status = TaskStatus(
            attempt_id=TaskAttemptID.parse(aid), is_map=tip.is_map,
            state=TaskState.SUCCEEDED, progress=1.0,
            phase=TaskPhase.MAP if tip.is_map else TaskPhase.REDUCE,
            start_time=finish - runtime, finish_time=finish,
            run_on_tpu=bool(rec.get("run_on_tpu", False)),
            tpu_device_id=int(rec.get("tpu_device_id", -1)))
        tip.attempts[aid] = status
        tip.next_attempt = max(tip.next_attempt,
                               int(rec.get("attempt", 0)) + 1)
        tip.state = "succeeded"
        tip.successful_attempt = aid
        tip.report.state = TaskState.SUCCEEDED
        tip.report.progress = 1.0
        tip.report.start_time = status.start_time
        tip.report.finish_time = finish
        tip.report.successful_attempt = aid
        self.history_logged.add(aid)
        if rec.get("counters"):
            self.counters.merge(Counters.from_dict(rec["counters"]))
        if tip.is_map:
            self._pending_maps.discard(tip.partition)
            self.finished_maps += 1
            self._record_runtime(runtime, is_map=True,
                                 on_tpu=status.run_on_tpu)
            tip.report.run_on_tpu = status.run_on_tpu
            tip.report.tpu_device_id = status.tpu_device_id
            # feed the hybrid profile so the recovered job's scheduler
            # means start where the interrupted job's left off
            if status.run_on_tpu:
                self.finished_tpu_maps += 1
                self._tpu_time_sum += runtime
            else:
                self.finished_cpu_maps += 1
                self._cpu_time_sum += runtime
        else:
            self._pending_reduces.discard(tip.partition)
            self.finished_reduces += 1
            self._reduce_time_sum += runtime
            self._record_runtime(runtime, is_map=False)

    def adopt_running_attempt(self, status: TaskStatus) -> bool:
        """A re-joining tracker reports ``status`` RUNNING and the
        master has no record of launching it (master restart, or the
        tracker was expired and re-contacted). Bind it to its TIP —
        in-flight work survives the restart — or return False: the
        caller kills the attempt individually (its task already
        succeeded through another attempt, was settled terminally, or
        the job is over). A blanket ``reinit`` never happens here."""
        with self.lock:
            if self.state != JobState.RUNNING:
                return False
            tip = self._tip_of(status.attempt_id.task)
            if tip is None:
                return False
            aid = str(status.attempt_id)
            if tip.state == "succeeded":
                # only the recorded winner survives; an unknown twin of
                # a finished task is a zombie to kill
                return tip.successful_attempt == aid
            prev = tip.attempts.get(aid)
            if prev is not None and prev.state in TaskState.TERMINAL:
                return False   # the master already settled it
            tip.attempts[aid] = status
            tip.next_attempt = max(tip.next_attempt,
                                   status.attempt_id.attempt + 1)
            # age anchor for the feedback model: adoption time is the
            # best master-local stand-in for the unknown dispatch time
            tip.dispatch_mono = tip.dispatch_mono or time.monotonic()
            if tip.state == "pending":
                tip.state = "running"
                if tip.is_map:
                    self._pending_maps.discard(tip.partition)
                else:
                    self._pending_reduces.discard(tip.partition)
            tip.report.state = TaskState.RUNNING
            tip.report.start_time = (tip.report.start_time
                                     or status.start_time or time.time())
            if tip.is_map:
                tip.report.run_on_tpu = status.run_on_tpu
                tip.report.tpu_device_id = status.tpu_device_id
            return True

    def kill(self) -> bool:
        """Transition to KILLED; returns True only for the caller that
        actually performed the transition (False if already terminal)."""
        with self.lock:
            if self.state in JobState.TERMINAL:
                return False
            self.state = JobState.KILLED
            self.finish_time = time.time()
            return True

    # ------------------------------------------------------------ wire

    _PLACEMENT_CAP = 50_000

    def _record_placement(self, run_on_tpu: bool) -> None:
        """One map assignment's backend, time-stamped relative to submit.
        Caller holds ``self.lock``."""
        if len(self.placement_series) >= self._PLACEMENT_CAP:
            self.placement_dropped += 1
            return
        self.placement_series.append(
            # offsets from the submit WALL stamp — the same zero the
            # history/trace timeline uses
            (round(time.time() - self.start_time, 3),  # tpulint: disable=clock-arith
             "T" if run_on_tpu else "c"))

    def placement_timeline(self) -> dict:
        """The convergence curve the hybrid scheduler is judged on
        (≈ JobQueueTaskScheduler.java:290-327 starvation rule observed
        from outside): the full assignment sequence ('TcccTTcT…') plus
        per-assignment timestamps, so a plot falls out of any finished
        run's history. Cumulative counts are derivable from ``seq`` in
        one pass — deliberately NOT serialized (a 50k-map job's history
        event would triple in size for redundant data)."""
        with self.lock:
            series = list(self.placement_series)
        return {"seq": "".join(b for _, b in series),
                "t": [t for t, _ in series],
                "dropped": self.placement_dropped}

    def status_dict(self) -> dict:
        with self.lock:
            return {
                "job_id": str(self.job_id),
                "state": self.state,
                "priority": self.priority,
                "map_progress": self.map_progress(),
                "reduce_progress": self.reduce_progress(),
                "finished_maps": self.finished_maps,
                "finished_tpu_maps": self.finished_tpu_maps,
                "finished_cpu_maps": self.finished_cpu_maps,
                "num_maps": len(self.maps),
                "num_reduces": len(self.reduces),
                "cpu_map_mean_time": self.cpu_map_mean_time(),
                "tpu_map_mean_time": self.tpu_map_mean_time(),
                "acceleration_factor": self.acceleration_factor(),
                # scheduling feedback: the live remaining-work model and
                # the targeted-speculation ledger (the "/job page's one
                # map is dragging this job" answer)
                "longest_remaining_path_s": round(
                    self.longest_remaining_path_s(), 3),
                "speculative_launched": self.speculative_launched,
                "speculative_won": self.speculative_won,
                "speculative_wasted": self.speculative_wasted,
                "speculative_in_flight": len(self._spec_attempts),
                # placement TAIL only: status_dict rides every polled
                # get_job_status RPC (clients poll at 5 Hz), so it must
                # stay small on 50k-map jobs; the full timeline ships
                # once, in the JOB_FINISHED history event
                "placement_seq": "".join(
                    b for _, b in self.placement_series[-512:]),
                # accelerator fault tolerance: demoted TIPs + the job-
                # level quarantine flag (the /job page's "why did my TPU
                # job go CPU" answer)
                "tpu_disabled": self.tpu_disabled,
                "tpu_demoted_tips": len(self._cpu_only_maps),
                # pipeline stage identity ("which stage/round is this
                # job", the /job page's link back to its /pipeline)
                "pipeline": str(confkeys.get(
                    self.conf, "tpumr.pipeline.id") or ""),
                "pipeline_node": str(confkeys.get(
                    self.conf, "tpumr.pipeline.node") or ""),
                "pipeline_round": confkeys.get_int(
                    self.conf, "tpumr.pipeline.round"),
                "error": self.error,
            }
