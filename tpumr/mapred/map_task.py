"""Map-side execution: the collect → sort → spill → merge pipeline.

≈ ``org.apache.hadoop.mapred.MapTask`` (reference: src/mapred/org/apache/
hadoop/mapred/MapTask.java, 1758 LoC): ``MapOutputBuffer`` (:869 — the
kvbuffer/kvindices in-memory ring), ``sortAndSpill`` (:1396 — partitioned
sort + combiner at spill time), ``mergeParts`` (:1621 — final merge of spills
into one IFile + index). The ring buffer's byte-level accounting is replaced
by a Python list with byte tallies; spill thresholds (io.sort.mb ×
io.sort.spill.percent) and the combiner-at-spill semantics are kept.

Runner selection ≈ MapTask.java:433-438: ``run_on_tpu`` picks the job's TPU
map runner (JobConf.get_tpu_map_runner_class) over the CPU MapRunner —
exactly where the reference chooses PipesGPUMapRunner.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Iterable, Iterator

from tpumr.core.counters import BackendCounter, Counters, TaskCounter
from tpumr.io import ifile
from tpumr.io.writable import serialize
from tpumr.mapred.api import OutputCollector, Reporter
from tpumr.mapred.split import InputSplit
from tpumr.mapred.task import Task, TaskPhase
from tpumr.utils.reflection import new_instance


class MapOutputBuffer:
    """In-memory partitioned k/v buffer with threshold spills."""

    def __init__(self, conf: Any, num_partitions: int, local_dir: str,
                 reporter: Reporter) -> None:
        self.conf = conf
        self.n_parts = max(1, num_partitions)
        self.local_dir = local_dir
        self.reporter = reporter
        self.partitioner = new_instance(conf.get_partitioner_class(), conf)
        self.comparator = conf.get_output_key_comparator()
        # combiner is instantiated per spill and closed after each combine
        # round (Hadoop semantics: CombinerRunner creates it per use) — this
        # also lets subprocess-backed combiners (StreamCombiner) finish their
        # child deterministically
        self.combiner_cls = conf.get_combiner_class()
        self.combiner = self.combiner_cls  # truthiness gate for callers
        self.codec = conf.compress_map_output
        self._buf: list[tuple[int, bytes, bytes]] = []
        self._bytes = 0
        self._threshold = int(conf.sort_mb * 1024 * 1024 * conf.spill_percent)
        self._spills: list[tuple[str, dict]] = []
        self._c_out_records = reporter.counters.counter(
            TaskCounter.FRAMEWORK_GROUP, TaskCounter.MAP_OUTPUT_RECORDS)
        self._c_out_bytes = reporter.counters.counter(
            TaskCounter.FRAMEWORK_GROUP, TaskCounter.MAP_OUTPUT_BYTES)
        os.makedirs(local_dir, exist_ok=True)

    # ------------------------------------------------------------ collect

    def collect(self, key: Any, value: Any) -> None:
        part = self.partitioner.get_partition(key, value, self.n_parts)
        if not 0 <= part < self.n_parts:
            raise ValueError(f"partition {part} out of range [0,{self.n_parts})")
        kb, vb = serialize(key), serialize(value)
        self._buf.append((part, kb, vb))
        self._bytes += len(kb) + len(vb) + 16
        # hoisted Counter objects: this runs once per map OUTPUT record
        self._c_out_records.increment()
        self._c_out_bytes.increment(len(kb) + len(vb))
        if self._bytes >= self._threshold:
            self.sort_and_spill()

    def collect_raw_batch(self, parts: "list[int]", kbs: "list[bytes]",
                          vbs: "list[bytes]") -> None:
        """Batched ingest for the TPU runner (whole kernel output at once).
        Same accounting and validation as the scalar :meth:`collect` path —
        including the spill threshold, checked at every crossing MID-batch:
        a kernel batch larger than ``io.sort.mb`` must spill as it lands,
        not overshoot the buffer by the whole batch."""
        nbytes = 0
        for p, kb, vb in zip(parts, kbs, vbs):
            if not 0 <= p < self.n_parts:
                raise ValueError(f"partition {p} out of range [0,{self.n_parts})")
            self._buf.append((p, kb, vb))
            nbytes += len(kb) + len(vb)
            self._bytes += len(kb) + len(vb) + 16
            if self._bytes >= self._threshold:
                self.sort_and_spill()
        self.reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                   TaskCounter.MAP_OUTPUT_RECORDS, len(kbs))
        self.reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                   TaskCounter.MAP_OUTPUT_BYTES, nbytes)

    # ------------------------------------------------------------ spill

    def sort_and_spill(self) -> None:
        """≈ MapTask.sortAndSpill (MapTask.java:1396)."""
        if not self._buf:
            return
        from tpumr.core import tracing
        with tracing.span("map:spill", records=len(self._buf),
                          bytes=self._bytes, spill=len(self._spills)):
            self._sort_and_spill_inner()

    def _sort_and_spill_inner(self) -> None:
        sk = self.comparator.sort_key
        self._buf.sort(key=lambda rec: (rec[0], sk(rec[1])))
        spill_path = os.path.join(self.local_dir,
                                  f"spill{len(self._spills)}.out")
        with open(spill_path, "wb") as f:
            w = ifile.Writer(f, codec=self.codec)
            idx = 0
            for part in range(self.n_parts):
                w.start_partition()
                lo = idx
                while idx < len(self._buf) and self._buf[idx][0] == part:
                    idx += 1
                records: "Iterator[tuple[bytes, bytes]]" = \
                    (rec[1:] for rec in self._buf[lo:idx])
                if self.combiner is not None:
                    records = self._combine(records)
                for kb, vb in records:
                    w.append_raw(kb, vb)
                w.end_partition()
            index = w.close()
        self.reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                   TaskCounter.SPILLED_RECORDS, len(self._buf))
        self._spills.append((spill_path, index))
        self._buf.clear()
        self._bytes = 0

    def _combine(self, records: "Iterable[tuple[bytes, bytes]]"
                 ) -> "Iterator[tuple[bytes, bytes]]":
        """Run the combiner over one partition's sorted record stream
        (≈ combiner invocation inside sortAndSpill) — STREAMING, one key
        group resident at a time (combine.combined_stream), never the
        whole partition."""
        from tpumr.mapred.combine import combined_stream
        return combined_stream(self.conf, self.combiner_cls,
                               self.comparator.sort_key, records,
                               self.reporter)

    # ------------------------------------------------------------ finish

    def flush(self) -> tuple[str, dict]:
        """Final spill + merge ≈ MapTask.mergeParts (MapTask.java:1621).
        Returns (output_path, index) of the single merged IFile."""
        self.sort_and_spill()
        final_path = os.path.join(self.local_dir, "file.out")
        if not self._spills:
            # empty output: one empty segment per partition
            with open(final_path, "wb") as f:
                w = ifile.Writer(f, codec=self.codec)
                for _ in range(self.n_parts):
                    w.start_partition()
                    w.end_partition()
                index = w.close()
            return final_path, index
        if len(self._spills) == 1:
            path, index = self._spills[0]
            os.replace(path, final_path)
            return final_path, index
        from tpumr.core import tracing
        with tracing.span("map:merge", spills=len(self._spills)):
            return self._merge_spills(final_path)

    def _merge_spills(self, final_path: str) -> tuple[str, dict]:
        """Final merge of the spill files (≈ mergeParts) with BOUNDED
        fan-in: ``io.sort.factor`` caps open streams / heap entries per
        pass (intermediate passes land in ``merge-tmp`` as IFile runs —
        io.merger.BoundedMerge), spill partitions stream through
        per-chunk file reads instead of one held-open fd per spill, and
        the combiner runs group-at-a-time over the merged stream instead
        of materializing the partition."""
        from tpumr.io import merger as merge_engine
        from tpumr.mapred.shuffle_copier import spill_region_segment
        sk = self.comparator.sort_key
        factor = self.conf.sort_factor
        run_dir = os.path.join(self.local_dir, "merge-tmp")
        with open(final_path, "wb") as f:
            w = ifile.Writer(f, codec=self.codec)
            for part in range(self.n_parts):
                w.start_partition()
                segs = [spill_region_segment(p, idx, part)
                        for p, idx in self._spills]
                bm = merge_engine.BoundedMerge(
                    segs, sk, factor, run_dir=run_dir,
                    reporter=self.reporter, prefix=f"spill-p{part}")
                try:
                    merged: "Iterator[tuple[bytes, bytes]]" = iter(bm)
                    if self.combiner is not None:
                        merged = self._combine(merged)
                    for kb, vb in merged:
                        w.append_raw(kb, vb)
                finally:
                    bm.close()
                w.end_partition()
            index = w.close()
        import shutil
        shutil.rmtree(run_dir, ignore_errors=True)
        for p, _ in self._spills:
            os.remove(p)
        return final_path, index


def localize_task_conf(conf: Any, task: Task) -> Any:
    """Per-attempt conf copy with the task's identity keys set ≈
    Task.localizeConfiguration (mapred.task.id / mapred.task.partition /
    mapred.task.is.map). A copy, not a mutation — tasks share the job conf
    and may run concurrently in one process."""
    from tpumr.mapred.jobconf import JobConf
    local = JobConf(conf)
    local.set("tpumr.task.attempt.id", str(task.attempt_id))
    local.set("tpumr.task.partition", task.partition)
    local.set("tpumr.task.is.map", task.is_map)
    return local


def run_map_task(conf: Any, task: Task, local_dir: str,
                 reporter: Reporter | None = None,
                 status: Any = None) -> tuple[str, dict]:
    """Execute one map attempt ≈ MapTask.run → runOldMapper
    (MapTask.java:340,402): read split, select CPU/TPU runner, collect into
    the buffer, flush to the merged IFile. Returns (output_path, index).

    Map-only jobs (num_reduces == 0) write through the OutputFormat into the
    committer work dir instead (reference behavior: NewDirectOutputCollector).
    """
    reporter = reporter or Reporter()
    conf = localize_task_conf(conf, task)
    from tpumr.utils.fi import fires, maybe_fail
    maybe_fail("map.task", conf)
    if fires("task.hang", conf) or fires(f"task.hang.m{task.partition}",
                                         conf):
        _hang_silently(reporter)
    if fires("task.slow", conf) or fires(f"task.slow.m{task.partition}",
                                         conf):
        _run_slowly(conf, reporter)
    split = InputSplit.from_dict(task.split) if task.split else None
    if split is not None and getattr(split, "path", None):
        # the split's source path, for mappers that dispatch per input
        # source (contrib.datajoin) ≈ map.input.file in the reference
        conf.set("tpumr.task.input.path", str(split.path))
    in_fmt = new_instance(conf.get_input_format(), conf)
    t0 = time.monotonic()

    if task.run_on_tpu:
        runner_cls = conf.get_tpu_map_runner_class()
        backend_tasks, backend_ms = (BackendCounter.TPU_MAP_TASKS,
                                     BackendCounter.TPU_MAP_MILLIS)
    else:
        runner_cls = _cpu_runner_class(conf)
        backend_tasks, backend_ms = (BackendCounter.CPU_MAP_TASKS,
                                     BackendCounter.CPU_MAP_MILLIS)

    def run_mapper(collector: Any) -> None:
        """Batch fast path when eligible, else the per-record runner —
        built HERE so a vectorized split never constructs (and
        configures) a throwaway runner+mapper pair."""
        if task.run_on_tpu or not _host_batch_fast_path(
                conf, in_fmt, split, collector, reporter):
            runner = new_instance(runner_cls, conf)
            reader = _counted_reader(in_fmt, split, conf, reporter)
            runner.run(reader, collector, reporter, task_ctx=task)

    if task.num_reduces == 0:
        from tpumr.mapred.output_formats import FileOutputCommitter
        committer = FileOutputCommitter(conf)
        wd = committer.setup_task(str(task.attempt_id))
        conf.set("tpumr.task.work.dir", wd)  # lib.MultipleOutputs seam
        out_fmt = new_instance(conf.get_output_format(), conf)
        writer = out_fmt.get_record_writer(conf, wd, task.partition)
        collector = OutputCollector(
            writer.write, getattr(writer, "write_fixed_rows", None))
        ok = False
        try:
            run_mapper(collector)
            ok = True
        finally:
            # same success gate as the reduce side: direct-write formats
            # (DBOutputFormat) must not flush a failed task's buffer
            abort = None if ok else getattr(writer, "abort", None)
            (abort or writer.close)()
        reporter.incr_counter(BackendCounter.GROUP, backend_tasks)
        reporter.incr_counter(BackendCounter.GROUP, backend_ms,
                              int((time.monotonic() - t0) * 1000))
        return "", {}

    # map-side named outputs (lib.MultipleOutputs) in jobs WITH reducers
    # write into the attempt's committer work dir; the dir is created
    # lazily by MultipleOutputs, and commit happens through the normal
    # gate only when files exist (FileOutputCommitter.needs_commit)
    from tpumr.mapred.output_formats import FileOutputCommitter
    _side_committer = FileOutputCommitter(conf)
    if _side_committer.fs is not None:
        conf.set("tpumr.task.work.dir",
                 _side_committer.work_dir(str(task.attempt_id)))

    from tpumr.mapred.device_shuffle import is_device_shuffle
    if is_device_shuffle(conf):
        # device-shuffled jobs skip sort/spill/partition entirely — the
        # reduce gang task does all three on the mesh (device_shuffle.py)
        from tpumr.mapred.device_shuffle import DenseMapOutputBuffer
        buffer: Any = DenseMapOutputBuffer(conf, local_dir, reporter)
        if _identity_dense_fast_path(conf, in_fmt, split, buffer, reporter):
            out = buffer.flush()
            reporter.incr_counter(BackendCounter.GROUP, backend_tasks)
            reporter.incr_counter(BackendCounter.GROUP, backend_ms,
                                  int((time.monotonic() - t0) * 1000))
            return out
    else:
        buffer = MapOutputBuffer(conf, task.num_reduces, local_dir, reporter)
    run_mapper(OutputCollector(buffer.collect))
    out = buffer.flush()
    reporter.incr_counter(BackendCounter.GROUP, backend_tasks)
    reporter.incr_counter(BackendCounter.GROUP, backend_ms,
                          int((time.monotonic() - t0) * 1000))
    return out


def _run_slowly(conf: Any, reporter: Reporter) -> None:
    """The ``task.slow`` chaos behavior: a straggler, not a hang — the
    attempt stays ALIVE and keeps reporting slowly-advancing progress
    for ``tpumr.fi.task.slow.ms`` before the real work runs. This is
    the seam the targeted-speculation tests and the straggler bench
    phase inject: progress ticks feed the master's per-TIP rate model
    (so the estimated finish lags honestly), while the kill-flag poll
    lets a speculative twin's win cancel the slow original promptly."""
    from tpumr.core import confkeys as _ck
    total_s = max(0.0, _ck.get_int(conf, "tpumr.fi.task.slow.ms") / 1000.0)
    t0 = time.monotonic()
    while True:
        elapsed = time.monotonic() - t0
        if elapsed >= total_s:
            return
        # crawl toward (but never reach) half done: honest "running but
        # way behind" telemetry for the remaining-work estimator
        reporter.progress(min(0.45, 0.45 * elapsed / total_s))
        reporter.raise_if_aborted()
        time.sleep(min(0.05, total_s - elapsed))


def _hang_silently(reporter: Reporter) -> None:
    """The ``task.hang`` chaos behavior: stop reporting progress forever
    — no counter ticks, no status, no progress — exactly the silent-
    but-alive attempt ``mapred.task.timeout`` exists for. Polls ONLY the
    kill flag: cooperative cancel is how an in-process reap frees the
    thread (isolated children are SIGKILLed regardless, and the poll is
    what keeps their umbilical kill-ping alive without counting as
    progress)."""
    while True:
        reporter.raise_if_aborted()
        time.sleep(0.05)


def _declared_mapper_class(conf: Any, attr: str):
    """The job's mapper class iff the class ITSELF declares ``attr``
    truthy (inherited flags don't count: a subclass overriding map()
    without re-declaring must not have its map() silently bypassed)."""
    mapper_cls = conf.get_class("mapred.mapper.class")
    if mapper_cls is not None and mapper_cls.__dict__.get(attr):
        return mapper_cls
    return None


def _read_batch_for_fast_path(conf: Any, in_fmt: Any, split: Any):
    """One RecordBatch for a vectorized map fast path, or None when the
    input shape is ineligible (no batch reader; dense splits have no
    byte keys to pass through). Shared gate for the identity-dense and
    host-batch-mapper paths so their eligibility can't drift apart."""
    if split is None or getattr(in_fmt, "read_batch", None) is None:
        return None
    from tpumr.mapred.split import DenseSplit
    if isinstance(split, DenseSplit):
        return None
    batch = in_fmt.read_batch(split, conf)
    if not hasattr(batch, "padded_keys"):
        return None  # DenseBatch-shaped input: no byte keys
    return batch


def _identity_dense_fast_path(conf: Any, in_fmt: Any, split: Any,
                              buffer: Any, reporter: Reporter) -> bool:
    """Device-shuffled identity maps (terasort: the mapper passes (k, v)
    through untouched, ``identity_map = True``) skip the per-record
    reader→map→collect loop entirely: the split arrives as one
    RecordBatch (vectorized SequenceFile/text parse) and lands in the
    dense buffer as two array appends. Falls back (False) whenever the
    shape doesn't fit — non-identity mapper, no batch input, or record
    widths that don't match the declared fixed layout (the width check
    needs the read, so THAT fallback re-reads the split — acceptable:
    it only happens on misconfigured fixed-width declarations)."""
    if _declared_mapper_class(conf, "identity_map") is None:
        return False
    batch = _read_batch_for_fast_path(conf, in_fmt, split)
    if batch is None:
        return False
    n = batch.num_records
    if n == 0:
        return True
    klens = batch.key_offsets[1:] - batch.key_offsets[:-1]
    vlens = batch.value_offsets[1:] - batch.value_offsets[:-1]
    if not ((klens == buffer.klen).all() and (vlens == buffer.vlen).all()):
        return False
    keys, _ = batch.padded_keys(buffer.klen)
    values, _ = batch.padded_values(buffer.vlen)
    buffer.collect_fixed_batch(keys, values)
    reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                          TaskCounter.MAP_INPUT_RECORDS, n)
    return True


def _host_batch_fast_path(conf: Any, in_fmt: Any, split: Any,
                          collector: Any, reporter: Reporter) -> bool:
    """Host-vectorized mapper seam: a mapper class that declares
    ``map_record_batch(batch, output, reporter)`` processes the whole
    split as ONE RecordBatch instead of the per-record reader→map loop
    (the host twin of a kernel's ``map_batch_cpu`` — example:
    TeraValidateMapper's consecutive-key order check)."""
    mapper_cls = _declared_mapper_class(conf, "map_record_batch")
    if mapper_cls is None:
        return False
    batch = _read_batch_for_fast_path(conf, in_fmt, split)
    if batch is None:
        return False
    # new_instance already ran configure(conf) — JobConfigurable seam
    mapper = new_instance(mapper_cls, conf)
    try:
        mapper.map_record_batch(batch, collector, reporter)
    finally:
        mapper.close()
    reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                          TaskCounter.MAP_INPUT_RECORDS, batch.num_records)
    return True


def _cpu_runner_class(conf: Any) -> type:
    """CPU runner selection: a kernel job whose kernel ships a vectorized
    host implementation (``map_batch_cpu``) processes batches on CPU slots
    too — the reference's hybrid premise (CPU slots carry real work,
    JobQueueTaskScheduler.java:127-178) demands a batch CPU path, not
    per-record Python. ``tpumr.cpu.batch.map=false`` opts out (e.g. to
    measure the per-record baseline)."""
    name = conf.get_map_kernel()
    if name and conf.get_boolean("tpumr.cpu.batch.map", True):
        from tpumr.mapred.tpu_runner import CpuBatchMapRunner
        from tpumr.ops import get_kernel
        if get_kernel(name).map_batch_cpu is not None:
            return CpuBatchMapRunner
    return conf.get_map_runner_class()


def _counted_reader(in_fmt: Any, split: InputSplit | None, conf: Any,
                    reporter: Reporter) -> Iterator[tuple[Any, Any]]:
    reader = in_fmt.get_record_reader(split, conf, reporter)
    c_in = reporter.counters.counter(TaskCounter.FRAMEWORK_GROUP,
                                     TaskCounter.MAP_INPUT_RECORDS)
    for i, (k, v) in enumerate(reader):
        if (i & 0x1FF) == 0:  # cooperative kill poll every 512 records —
            reporter.raise_if_aborted()  # preemption frees the slot NOW
        c_in.increment()
        yield k, v
