"""Entry point for ONE master shard process.

``python -m tpumr.mapred.shard_worker`` reads a single JSON spec line
from stdin, boots a full :class:`~tpumr.mapred.jobtracker.JobMaster`
scoped to this shard (own history subdir, own cluster-id suffix, HTTP
off — the coordinator serves the merged surface), registers with the
coordinator, then blocks on stdin until EOF. Stdin doubles as the
parent-death channel: if the coordinator dies, the pipe closes and the
shard shuts itself down instead of orphaning — same trick as
``subprocess`` daemons everywhere, no PID polling required.

The spec::

    {"index": 0, "host": "127.0.0.1", "port": 0,
     "coordinator": ["127.0.0.1", 54321], "conf": {...}}

``port`` is 0 on first spawn (the shard binds an ephemeral port and
reports it via ``register_shard``) and PINNED on respawn: a re-joining
tracker fleet keeps its shard map, so a respawned shard must come back
on the address its trackers already know — exactly the master-restart
contract from the adoption protocol, scoped to one shard.
"""

from __future__ import annotations

import json
import os
import sys
import time


def build_shard_conf(spec: dict):
    """The shard's JobConf: the coordinator's conf plus the shard
    scoping overrides. Shared conf means shared RPC secret — the
    coordinator, shards, and fleet all derive the same one."""
    from tpumr.mapred.jobconf import JobConf
    conf = JobConf()
    for key, value in (spec.get("conf") or {}).items():
        conf.set(key, value)
    k = int(spec["index"])
    base = conf.get("tpumr.history.dir") or ""
    if base:
        # each shard recovers from ITS OWN event log on respawn;
        # sibling shards' histories must be invisible to it
        conf.set("tpumr.history.dir", os.path.join(str(base), f"shard-{k}"))
    # distinct cluster-id suffix per shard: two shards booting in the
    # same millisecond must not mint colliding job ids
    conf.set("tpumr.cluster.id.suffix", f"s{k}")
    # a killed shard is a master restart scoped to its trackers —
    # recovery is non-negotiable here, whatever the outer conf says
    conf.set("mapred.jobtracker.restart.recover", True)
    conf.set("tpumr.master.shards", 0)        # no recursive sharding
    conf.set("mapred.job.tracker.http.port", -1)
    return conf


def serve(spec: dict) -> int:
    from tpumr.ipc.rpc import RpcClient
    from tpumr.mapred.jobtracker import JobMaster
    from tpumr.security import rpc_secret

    conf = build_shard_conf(spec)
    host = str(spec.get("host") or "127.0.0.1")
    port = int(spec.get("port") or 0)
    master = None
    if port:
        # respawn on a pinned port: the dead shard's listener may
        # linger in TIME_WAIT for a few hundred ms
        for attempt in range(250):
            try:
                master = JobMaster(conf, host=host, port=port)
                break
            except OSError:
                if attempt == 249:
                    raise
                time.sleep(0.02)
    else:
        master = JobMaster(conf, host=host, port=0)
    assert master is not None
    master.start()
    try:
        coord_host, coord_port = spec["coordinator"]
        reg = RpcClient(str(coord_host), int(coord_port),
                        secret=rpc_secret(conf))
        try:
            reg.call("register_shard", int(spec["index"]),
                     master.address[0], master.address[1], os.getpid())
        finally:
            reg.close()
        sys.stdin.buffer.read()   # parent-death watch: EOF = shut down
        return 0
    finally:
        master.stop()


def main() -> int:
    line = sys.stdin.readline()
    if not line.strip():
        print("shard_worker: no spec on stdin", file=sys.stderr)
        return 2
    return serve(json.loads(line))


if __name__ == "__main__":
    sys.exit(main())
