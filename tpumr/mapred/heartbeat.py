"""Heartbeat wire encoding — full statuses vs change-only deltas.

A tracker's status dict is mostly static: slot maxima, host names,
device lists, and health flags change rarely, yet every beat used to
re-ship (and the master to re-deserialize and re-store) all of them.
With delta encoding (``tpumr.heartbeat.delta``, default on) a tracker
sends the FULL status on initial contact and, afterwards, only the keys
whose values changed since the last beat the master is known to have
received — so an idle tracker's beat shrinks to a near-empty dict
(``rpc_heartbeat_request_bytes`` is the series that shows it) and the
master's per-beat fold touches proportionally less state.

Three key classes:

- **baseline keys** (slot counts, devices, health, memory): shipped
  only when changed; the master inherits the previous value otherwise.
- **per-beat keys** (``task_statuses``, ``fetch_failures``): describe
  THIS beat only — shipped when non-empty, never inherited by the
  master (a delta without them means "none this beat", not "same as
  last beat").
- **metrics piggyback**: cumulative by design (metrics/cluster.py), so
  an unchanged snapshot is safely omitted — the master's fold of the
  last one already holds. Idle trackers skip both the merge cost and
  the bytes.

Delivery contract: the encoder diffs against the last status the
master has SEEN. ``delivered()`` commits a beat's baseline only after
the RPC returned; any failed/uncertain call must ``reset()`` so the
next beat re-ships the full status (a delta against a baseline the
master never stored — or stored a newer version of — would silently
corrupt its view: a key that changed and changed back across a lost
beat would never be corrected). A master that has no baseline for a
delta (restart, eviction) answers ``reinit``, which also resets the
encoder via the tracker's normal reinit handling.
"""

from __future__ import annotations

from typing import Any

#: status keys that describe one beat and are never inherited when the
#: master reconstructs a full status from a delta
PER_BEAT_KEYS = ("task_statuses", "fetch_failures", "metrics")

_MISSING = object()


class HeartbeatEncoder:
    """Client-side (tracker) half of the delta protocol."""

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = bool(enabled)
        self._base: "dict | None" = None
        self._metrics: Any = None
        self._pending: "tuple[dict, Any] | None" = None

    def encode(self, full: dict, metrics: Any = None) -> dict:
        """The wire status for one beat: ``full`` verbatim (plus the
        piggyback) when delta is off or no delivered baseline exists,
        else a change-only dict flagged ``delta: True``. Call
        :meth:`delivered` after the RPC succeeds."""
        base = {k: v for k, v in full.items() if k not in PER_BEAT_KEYS}
        self._pending = (base, metrics)
        if not self.enabled or self._base is None:
            status = dict(full)
            if metrics is not None:
                status["metrics"] = metrics
            return status
        prev = self._base
        status: dict = {"tracker_name": full.get("tracker_name"),
                        "delta": True}
        for k, v in base.items():
            if prev.get(k, _MISSING) != v:
                status[k] = v
        for k in ("task_statuses", "fetch_failures"):
            if full.get(k):
                status[k] = full[k]
        if metrics is not None and metrics != self._metrics:
            status["metrics"] = metrics
        return status

    def will_delta(self) -> bool:
        """Will the next :meth:`encode` produce a change-only beat?
        Callers use this to bypass their own per-key suppression (e.g.
        the RUNNING-status report-rate limit) when a FULL beat is due —
        a full beat must carry everything, it resets the master's
        believed-running set."""
        return self.enabled and self._base is not None

    def delivered(self) -> None:
        """The master received the last encoded beat — its view now
        includes that beat, so future deltas may build on it."""
        if self._pending is not None:
            base, metrics = self._pending
            self._base = base
            # a piggyback-less beat leaves the master's last-merged
            # metrics untouched — clobbering the baseline to None here
            # would make every later unchanged snapshot look new and
            # re-ship it, defeating the suppression
            if metrics is not None:
                self._metrics = metrics
            self._pending = None

    def reset(self) -> None:
        """Forget the baseline (failed RPC, reinit): the next beat
        ships the full status."""
        self._base = None
        self._metrics = None
        self._pending = None


def fold_delta(prev_full: dict, status: dict) -> dict:
    """Master-side half: reconstruct a full status from a change-only
    beat against the previous full status. A non-delta ``status``
    passes through (minus any stray flag). Per-beat keys never inherit
    from ``prev_full`` — absent means none this beat."""
    if not status.get("delta"):
        status.pop("delta", None)
        return status
    full = {k: v for k, v in prev_full.items() if k not in PER_BEAT_KEYS}
    full.update(status)
    full.pop("delta", None)
    return full
