"""NodeRunner — the per-host worker daemon.

≈ ``org.apache.hadoop.mapred.TaskTracker`` (reference: src/mapred/org/
apache/hadoop/mapred/TaskTracker.java, 4636 LoC). Reproduced contracts:

- the heartbeat loop (offerService :1706-1775 / transmitHeartBeat
  :1789-1860): status with BOTH pool maxima, ``ask_for_new_task`` when
  either pool has room (:1841-1844), response-id resend protocol;
- **dual slot pools** (:331-333, :1427-1432): separate CPU and TPU map slot
  maxima; the launcher gates each task on the pool matching its
  ``run_on_tpu`` flag (TaskLauncher :2502-2628) and frees the right pool on
  completion/kill (:3401-3402);
- per-device accounting: free TPU device ids derived from running task
  statuses (availableGPUDevices, TaskTrackerStatus.java:536-550) and
  shipped in every heartbeat;
- the shuffle server role (MapOutputServlet :4050): map outputs are served
  per (job, map, partition) over the tracker's RPC port;
- task execution in-process on threads by default (the reference forks
  child JVMs via TaskRunner/JvmManager — an explicit re-design: kernels
  must share the host process to share the JAX runtime and HBM split
  cache). ``tpumr.task.isolation=process`` opts CPU map/reduce attempts
  into real child processes (process_runner.py ≈ TaskRunner/JvmManager,
  child.py ≈ Child.java) talking back over the umbilical_* RPC methods
  (≈ TaskUmbilicalProtocol), optionally launched through the native
  setuid task-controller.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
import time
import traceback
from typing import Any

from tpumr.core.counters import Counters
from tpumr.io import compress
from tpumr.io.fdcache import FdCache
from tpumr.core import confkeys
from tpumr.io import ifile
from tpumr.ipc.rpc import RpcClient, RpcClientPool, RpcServer
from tpumr.mapred.api import Reporter, TaskKilledError
from tpumr.mapred.ids import TaskAttemptID, TaskID
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.jobtracker import PROTOCOL_VERSION
from tpumr.mapred.map_task import run_map_task
from tpumr.mapred.output_formats import FileOutputCommitter
from tpumr.mapred.reduce_task import run_reduce_task
from tpumr.mapred.task import Task, TaskPhase, TaskState, TaskStatus


def _resolvable(host: str) -> bool:
    import socket
    try:
        socket.getaddrinfo(host, None)
        return True
    except OSError:
        return False


class MapLocator:
    """Map-output location resolution ≈ the ReduceCopier's polling of
    TaskCompletionEvents (ReduceTask.java:659 fetch loop). ``events_fn
    (cursor) -> [event]`` is the master's incremental completion-event
    feed (called directly by the tracker, via the umbilical by isolated
    child processes). Calling ``locate(map_index)`` returns a
    :class:`_ShuffleTarget` bound to the serving tracker's shuffle RPC —
    RpcClient-shaped for one-shot ``.call``, plus ``lease``/``release``
    over the locator's shared connection pool for pipelined streams.

    The completion-event feed is APPEND-ONLY: a map output withdrawn by
    the master (lost tracker, too-many-fetch-failures re-execution)
    arrives as an OBSOLETE-status event that evicts the cached location;
    ``invalidate`` lets the ShuffleCopier drop a location it observed
    dead itself, so the next locate() round blocks until the re-run
    map's fresh completion event supplies the new address — mid-shuffle,
    without restarting the copy phase."""

    def __init__(self, events_fn: Any, secret: bytes | None,
                 poll_s: float = 0.2, timeout_s: float = 600.0,
                 scope: "str | None" = None,
                 conns_per_target: int = 2) -> None:
        self._events_fn = events_fn
        self._secret = secret
        self._poll_s = poll_s
        self._timeout_s = timeout_s
        self._scope = scope
        #: liveness seam for the hung-task reaper: invoked once per poll
        #: iteration while a caller blocks waiting for a map location
        #: (the ShuffleCopier wires the reduce Reporter's keepalive here
        #: — a reduce stalled on a not-yet-rerun map is waiting, not
        #: hung, and must not be reaped at mapred.task.timeout)
        self.on_wait: "Any | None" = None
        self._events: dict[int, dict] = {}
        #: invalidated-but-not-withdrawn locations: the feed is cursor-
        #: based (an old SUCCEEDED event is never re-sent), so a
        #: location WE dropped must stay available as a fallback until
        #: the master actually replaces or withdraws it — otherwise one
        #: reducer's asymmetric fetch fault would strand it blocking for
        #: a re-run the master never schedules
        self._stale: dict[int, dict] = {}
        self._seen = 0
        #: consecutive polls that surfaced nothing while a caller was
        #: starving — past a threshold the cursor rewinds to 0 (see
        #: __call__): a cursor minted before a master restart can sit
        #: past the resubmitted job's shorter feed, hiding recovered
        #: events; re-folding from 0 is idempotent
        self._empty_polls = 0
        # shared per-target connection pool: parallel.copies fetcher
        # threads multiplex pipelined fetches over conns_per_target
        # sockets per tracker, reused across fetches and across the
        # penalty-box recovery path — not one serialized client per
        # (addr, thread) opened anew by every fetcher
        self.pool = RpcClientPool(
            lambda host, port: RpcClient(host, port, secret=secret,
                                         scope=scope),
            conns_per_target=conns_per_target)
        # the ShuffleCopier drives locate() from parallel fetcher
        # threads. cache_lock guards the event cache/cursor; poll_lock
        # serializes the events_fn RPC OUTSIDE cache_lock, so threads
        # whose map is already cached never wait behind a network poll
        # — and the cursor can't double-advance (that silently skips
        # events forever).
        self._cache_lock = threading.Lock()
        self._poll_lock = threading.Lock()

    def _cached(self, map_index: int) -> bool:
        with self._cache_lock:
            return map_index in self._events

    def _fold(self, fresh: "list[dict]") -> None:
        """Apply one batch of completion events to the location cache.
        Caller holds ``_cache_lock``."""
        self._seen += len(fresh)
        for e in fresh:
            idx = e["map_index"]
            if e.get("status") == "OBSOLETE":
                cur = self._events.get(idx)
                if cur is not None and cur["attempt_id"] == e["attempt_id"]:
                    del self._events[idx]
                st = self._stale.get(idx)
                if st is not None and st["attempt_id"] == e["attempt_id"]:
                    # genuinely withdrawn: the fallback dies too — now
                    # we really do block for the re-run's fresh event
                    del self._stale[idx]
            else:
                self._events[idx] = e
                self._stale.pop(idx, None)

    def _entry(self, map_index: int) -> "dict | None":
        """Caller holds ``_cache_lock``."""
        e = self._events.get(map_index)
        return e if e is not None else self._stale.get(map_index)

    def attempt_of(self, map_index: int) -> str:
        """The map attempt whose output the (possibly stale) cached
        location serves — what a fetch-failure report names to the
        master."""
        with self._cache_lock:
            e = self._entry(map_index)
            return e["attempt_id"] if e is not None else ""

    def addr_of(self, map_index: int) -> str:
        with self._cache_lock:
            e = self._entry(map_index)
            return e["shuffle_addr"] if e is not None else ""

    def size_of(self, map_index: int) -> int:
        """Total map-output bytes the cached completion event advertised
        (0 when unknown) — the ShuffleCopier's largest-first fetch
        ordering key. Advisory only: a 0 never blocks a fetch."""
        with self._cache_lock:
            e = self._entry(map_index)
            return int(e.get("output_bytes", 0) or 0) if e is not None else 0

    def invalidate(self, map_index: int) -> None:
        """Demote the cached location to a fallback: the next locate()
        round polls for a fresh event first, but while the master keeps
        the output live (other reducers may fetch it fine — the fault
        could be ours) the known location keeps serving retries."""
        with self._cache_lock:
            e = self._events.pop(map_index, None)
            if e is not None:
                self._stale[map_index] = e

    def __call__(self, map_index: int) -> "_ShuffleTarget":
        return _ShuffleTarget(self.pool, self.resolve(map_index))

    def resolve(self, map_index: int) -> str:
        """Block until the map's serving address is known and return it
        ("host:port") — resolution WITHOUT binding a connection, so a
        streaming fetch resolves once per segment and a mid-fetch
        OBSOLETE fold can't flip a healthy in-flight stream."""
        # monotonic deadline: an NTP step mid-shuffle must neither fire
        # the timeout early nor stall it past the configured bound
        deadline = time.monotonic() + self._timeout_s
        while True:
            with self._cache_lock:
                # event read under the SAME lock hold that checked it: a
                # concurrent _fold of an OBSOLETE withdrawal between a
                # cached() check and a later read would KeyError
                e = self._events.get(map_index)
                if e is not None:
                    addr = e["shuffle_addr"]
                    break
            with self._poll_lock:
                if self._cached(map_index):  # another poller just fetched
                    continue
                try:
                    fresh = self._events_fn(self._seen)
                except Exception:  # noqa: BLE001 — master briefly down
                    # (restarting): a reduce mid-shuffle survives the
                    # control-plane outage by simply polling again; the
                    # deadline below bounds how long, and on_wait keeps
                    # the reaper informed that we are waiting, not hung
                    fresh = []
                with self._cache_lock:
                    self._fold(fresh)
                if fresh:
                    self._empty_polls = 0
            if self._cached(map_index):
                continue
            self._empty_polls += 1
            if self._empty_polls >= 25:
                # starving on an empty feed: the cursor may predate a
                # master restart (the recovered feed restarted at 0) —
                # rewind and re-fold everything (idempotent)
                self._empty_polls = 0
                with self._cache_lock:
                    self._seen = 0
            with self._cache_lock:
                stale = self._stale.pop(map_index, None)
                if stale is not None:
                    # nothing fresh after a poll: the invalidated
                    # location is still the best known — reinstate it
                    # (retries keep hammering it through the penalty
                    # box) until the master replaces or withdraws it
                    self._events[map_index] = stale
                    continue
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"map {map_index} output never became available")
            if self.on_wait is not None:
                self.on_wait()
            time.sleep(self._poll_s)
        return addr

    def close(self) -> None:
        self.pool.close()


class _ShuffleTarget:
    """One resolved shuffle target over the locator's shared connection
    pool. ``call`` leases a pooled connection for exactly one RPC (the
    legacy per-call sites: dense fetch, handoff probe); ``lease`` hands
    the caller an exclusive RpcClient for a pipelined call_begin/
    call_finish window, paired with ``release``. The address is fixed at
    construction — re-resolution is the LOCATOR's job, on failure."""

    __slots__ = ("pool", "addr")

    def __init__(self, pool: RpcClientPool, addr: str) -> None:
        self.pool = pool
        self.addr = addr

    @property
    def host(self) -> str:
        return self.addr.rsplit(":", 1)[0]

    @property
    def port(self) -> int:
        return int(self.addr.rsplit(":", 1)[1])

    def call(self, method: str, *params: Any) -> Any:
        cli = self.pool.acquire(self.addr)
        dead = False
        try:
            return cli.call(method, *params)
        except (ConnectionError, OSError):
            dead = True
            raise
        finally:
            self.pool.release(self.addr, cli, dead=dead)

    def lease(self) -> RpcClient:
        return self.pool.acquire(self.addr)

    def release(self, cli: RpcClient, dead: bool = False) -> None:
        self.pool.release(self.addr, cli, dead=dead)


def make_map_locator(events_fn: Any, secret: bytes | None,
                     poll_s: float = 0.2, timeout_s: float = 600.0,
                     scope: "str | None" = None,
                     conns_per_target: int = 2) -> MapLocator:
    """Factory kept for the existing call sites (tracker + child)."""
    return MapLocator(events_fn, secret, poll_s=poll_s,
                      timeout_s=timeout_s, scope=scope,
                      conns_per_target=conns_per_target)


#: PR 13's shuffle-serving fd LRU, since promoted to the shared
#: tpumr.io.fdcache engine (the datanode block read path uses the same
#: cache); the name is kept for the existing shuffle call sites.
SpillFdCache = FdCache


#: wire compression for served chunks moved to tpumr.io.compress
#: (shared with the datanode); aliases keep the shuffle call sites and
#: tests unchanged
_WIRE_MIN_BYTES = compress.WIRE_MIN_BYTES
_wire_compress = compress.wire_compress


def serve_chunk(fds: SpillFdCache, path: str, index: dict,
                partition: int, offset: int, max_bytes: int,
                max_chunk: int, wire: str = "none") -> dict:
    """One bounded chunk of one partition segment, pread off the fd
    cache. The chunk length is DETERMINISTIC — ``min(max_bytes,
    max_chunk, remaining)`` in payload space — which is what lets a
    pipelining client schedule follow-up offsets before their
    predecessors arrive. Shared by the tracker's RPC methods and the
    bench/test serving stubs."""
    off, raw_len, part_len = index["partitions"][partition]
    payload_len = part_len - 4          # minus the length prefix
    offset = max(0, int(offset))
    n = max(0, min(int(max_bytes), max_chunk, payload_len - offset))
    data = fds.pread(path, n, off + 4 + offset)
    out = {"data": data, "total": payload_len, "raw": raw_len,
           "codec": index.get("codec", "none"), "n": n}
    _wire_compress(out, wire)
    return out


def serve_batch(fds: SpillFdCache, lookup: Any, partition: int,
                map_indexes: "list[int]", max_bytes_each: int,
                max_total_bytes: int, max_chunk: int,
                wire: str = "none") -> "list[dict]":
    """Many small segments from ONE tracker in ONE response frame — the
    small-segment regime where per-call overhead dominates the copy
    phase. ``lookup(map_index) -> (path, index)`` raises to fail THAT
    entry alone: the error rides back as ``{"map_index", "error"}`` so
    one lost map triggers the fetch-failure protocol for exactly that
    map while the rest of the batch lands. The total-bytes budget stops
    the batch early (≥1 entry always served; omitted indexes are simply
    absent and the copier requeues them); an entry bigger than its
    per-entry cap arrives as a prefix the copier continues chunked."""
    out: "list[dict]" = []
    budget = max(1, int(max_total_bytes))
    for m in map_indexes:
        if budget <= 0 and out:
            break
        try:
            path, index = lookup(m)
            ent = serve_chunk(fds, path, index, partition, 0,
                              min(int(max_bytes_each), budget)
                              if out else int(max_bytes_each),
                              max_chunk, wire)
        except Exception as e:  # noqa: BLE001 — per-entry failure seam
            out.append({"map_index": m, "error": f"{type(e).__name__}: {e}"})
            continue
        ent["map_index"] = m
        budget -= len(ent["data"])
        out.append(ent)
    return out


class NodeRunner:
    def __init__(self, master_host: str, master_port: int, conf: JobConf,
                 name: str | None = None, host: str = "127.0.0.1",
                 n_tpu_devices: int | None = None,
                 bind_host: str | None = None) -> None:
        self.conf = conf
        #: locality name reported to the scheduler (may be a fake topology
        #: name ≈ MiniMRCluster hosts ctor args)
        self.host = host
        #: routable address the RPC/shuffle server binds and advertises
        self.bind_host = bind_host or ("127.0.0.1" if host and not
                                       _resolvable(host) else host)
        self.name = name or f"tracker_{host}_{id(self) & 0xffff}"
        from tpumr.security import rpc_secret
        self._rpc_secret = rpc_secret(conf)
        # control-plane partition tolerance: the master channel retries
        # transport failures with capped jittered backoff before giving
        # up (tpumr.rpc.client.*); the heartbeat loop's lost-master
        # state handles outages longer than one call's retry budget
        self.master = RpcClient(
            master_host, master_port, secret=self._rpc_secret,
            retries=confkeys.get_int(conf, "tpumr.rpc.client.retries"),
            backoff_ms=confkeys.get_int(conf, "tpumr.rpc.client.backoff.ms"))
        self.master.fi_conf = conf   # rpc.drop/delay/reset chaos seams
        remote_version = self.master.call("get_protocol_version")
        if remote_version != PROTOCOL_VERSION:
            raise RuntimeError(f"master protocol {remote_version} != "
                               f"{PROTOCOL_VERSION}")

        # rack resolved tracker-side at startup (outside any master lock —
        # the scheduler must never exec the topology script mid-heartbeat)
        from tpumr.net import resolver_from_conf
        self.rack = resolver_from_conf(conf)(self.host)

        self.max_cpu_map_slots = conf.max_cpu_map_slots
        self.max_tpu_map_slots = conf.max_tpu_map_slots
        self.max_reduce_slots = conf.max_reduce_slots
        self.n_tpu_devices = (n_tpu_devices if n_tpu_devices is not None
                              else max(1, self.max_tpu_map_slots))
        self.heartbeat_s = conf.get_int("tpumr.heartbeat.interval.ms", 1000) / 1000.0

        self.lock = threading.RLock()
        self.running: dict[str, TaskStatus] = {}      # attempt -> status
        self.running_tasks: dict[str, Task] = {}
        self._kill_requested: set[str] = set()
        self.map_outputs: dict[tuple[str, int], tuple[str, dict]] = {}
        self.job_confs: dict[str, JobConf] = {}
        # ≈ mapred.local.dir: tracker-local scratch root — when set it must
        # match the task-controller's allowed.local.dirs policy
        local_base = conf.get("mapred.local.dir")
        if local_base:
            os.makedirs(local_base, exist_ok=True)
        self.local_root = tempfile.mkdtemp(prefix=f"tpumr-{self.name}-",
                                           dir=local_base or None)
        self._response_id = 0
        self._initial_contact = True
        # heartbeat delta encoding (tpumr.heartbeat.delta, default on):
        # full status on (re)contact, change-only beats afterwards — an
        # idle tracker's beat is a near-empty dict on the wire
        from tpumr.mapred.heartbeat import HeartbeatEncoder
        self._hb_encoder = HeartbeatEncoder(
            confkeys.get_boolean(conf, "tpumr.heartbeat.delta"))
        #: the metrics piggyback rides at most this often (cumulative
        #: state — freshness is a seconds-scale concern, and building
        #: the typed snapshot every beat is pure overhead on fast-
        #: heartbeat clusters; 0 = every beat, the default, where the
        #: delta encoder still drops piggybacks that didn't change)
        self._piggyback_interval_s = conf.get_int(
            "tpumr.metrics.piggyback.interval.ms", 0) / 1000.0
        self._piggyback_last = 0.0
        #: RUNNING-status report-rate limit (delta beats only): a status
        #: whose state/phase didn't change rides the wire at most once
        #: per this interval — continuous progress movement otherwise
        #: re-ships (and the master re-folds) every running task on
        #: every beat. State transitions and terminal statuses always
        #: ship. 0 = every beat. The master's believed-running set
        #: tolerates the gaps (delta beats add/remove incrementally).
        self._status_interval_s = conf.get_int(
            "tpumr.task.status.report.interval.ms", 1000) / 1000.0
        #: aid -> (state, phase, monotonic of last ship)
        self._status_shipped: "dict[str, tuple]" = {}
        self._stop = threading.Event()
        self._hb_count = 0
        # --- lost-master state (master restart survival) ---
        #: True while the master is unreachable at the TRANSPORT level
        #: (connect refused / reset / timeout) — in-flight tasks keep
        #: running, heartbeats retry with capped jittered backoff, and
        #: on re-contact the master ADOPTS the full status instead of
        #: answering reinit. Application-level RPC errors (the master
        #: answered, unhappily) never enter this state.
        self.master_unreachable = False
        self._master_failures = 0
        self._last_master_contact = time.monotonic()
        self._lost_master_backoff_max_s = conf.get_int(
            "tpumr.heartbeat.lostmaster.backoff.max.ms", 15_000) / 1000.0
        #: old job id -> resubmitted id, taught by a recovered master's
        #: recover_job actions: future map-output registrations under
        #: the old id are stored under the new one (existing entries
        #: are re-keyed on receipt), so NEW-id reducers can fetch
        #: outputs produced before the restart
        self._job_rebinds: dict[str, str] = {}
        #: upstream job id -> shared HandoffSource for streamed-
        #: pipeline downstream maps on this tracker (one MapLocator per
        #: upstream stage, every map task of the stage shares it)
        self._handoff_sources: dict[str, Any] = {}
        # per-pool gating ≈ TaskLauncher's numCPUFreeSlots/numGPUFreeSlots
        # wait loops (TaskTracker.java:2502-2628): even if the master ever
        # over-assigns, a task blocks until ITS pool has a slot
        self._cpu_sem = threading.Semaphore(max(1, self.max_cpu_map_slots))
        self._tpu_sem = threading.Semaphore(max(1, self.max_tpu_map_slots))
        self._red_sem = threading.Semaphore(max(1, self.max_reduce_slots))

        # shuffle server = this tracker's RPC surface (MapOutputServlet
        # role) — reactor-served by default: shuffle reads ride the
        # selector loop's bounded handler pool (saturation answered and
        # counted, rpc_pool_saturated) and pipelining fetchers keep
        # several chunk requests in flight per connection. The knob is
        # the escape hatch back to thread-per-connection.
        use_reactor = confkeys.get_boolean(conf, "tpumr.tasktracker.reactor")
        self._server = RpcServer(self, host=self.bind_host, port=0,
                                 secret=self._rpc_secret,
                                 reactor=use_reactor,
                                 fast_methods={"get_protocol_version",
                                               "umbilical_ping"}
                                 if use_reactor else None)
        # shuffle reads are idempotent byte-range reads: opt them out of
        # the replay cache so MiB-scale chunk responses never pin the
        # response stripes (and replays simply re-read)
        self._server.uncached_methods = {
            "get_map_output", "get_map_output_chunk",
            "get_map_output_dense", "get_map_outputs_batch",
        }
        #: serving-side LRU of open spill fds (os.pread per chunk — no
        #: per-chunk open/seek; invalidated on job purge/rebind)
        self._spill_fds = SpillFdCache(
            confkeys.get_int(conf, "tpumr.shuffle.fd.cache.size"))
        # task children authenticate with their JOB token, not the
        # cluster secret (≈ JobTokenSecretManager + SecureShuffleUtils):
        # scoped callers may reach only the umbilical + shuffle surface,
        # and the methods themselves pin the scope to the job argument
        self._job_tokens: dict[str, bytes] = {}
        #: scope -> monotonic retry-at (negative cache deadlines must
        #: not stretch/shrink with wall-clock steps)
        self._job_token_misses: dict[str, float] = {}
        self._miss_budget = 20.0            # token bucket for miss lookups
        self._miss_budget_ts = time.monotonic()
        self._server.token_resolver = self._job_token_or_none
        self._server.scoped_methods = {
            "get_protocol_version", "umbilical_ping", "umbilical_status",
            "umbilical_can_commit", "umbilical_events", "umbilical_done",
            "umbilical_fail", "umbilical_report_fetch_failure",
            "get_map_output", "get_map_output_chunk",
            "get_map_output_dense", "get_map_outputs_batch",
        }
        #: fetch-failure reports from this tracker's reduces (in-process
        #: or via the umbilical), forwarded to the master on the next
        #: heartbeat and dropped only once a heartbeat delivered them
        self._fetch_failures: list[dict] = []
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           name=f"{self.name}-heartbeat",
                                           daemon=True)

        # instrumentation ≈ TaskTrackerInstrumentation/TaskTrackerMXBean
        from tpumr.metrics import MetricsSystem
        self.metrics = MetricsSystem(
            "tasktracker",
            period_s=confkeys.get_int(conf, "tpumr.metrics.period.ms") / 1000)
        self._mreg = self.metrics.new_registry(self.name)
        self._mreg.set_gauge("running", lambda: dict(zip(
            ("cpu_maps", "tpu_maps", "reduces"), self._counts())))
        self._mreg.set_gauge("slots", lambda: {
            "cpu": self.max_cpu_map_slots, "tpu": self.max_tpu_map_slots,
            "reduce": self.max_reduce_slots})
        # per-pool busy fractions: the device-utilization signal the
        # hybrid/job-driven scheduling work consumes (PAPERS.md), and
        # the per-tracker rows behind the master's cluster view
        self._mreg.set_gauge("slot_utilization", self._slot_utilization)
        # lost-master visibility: whether the control plane is reachable
        # from HERE, and how stale the lease is — the first thing to
        # check when a tracker looks wedged (the dashboards' twin of the
        # master-side heartbeat-age column)
        self._mreg.set_gauge("master_unreachable",
                             lambda: 1 if self.master_unreachable else 0)
        self._mreg.set_gauge(
            "master_contact_age_s",
            lambda: round(time.monotonic() - self._last_master_contact,
                          3))
        # RPC server-side latency per method — the tracker's RPC surface
        # IS the shuffle server (get_map_output_chunk) + the umbilical
        self._server.metrics = self.metrics.new_registry("rpc")
        # claim the process-wide data-plane registries (shuffle fetch,
        # TPU runner) for publication: exactly one co-located tracker
        # may publish each, or the master would double-merge increments
        from tpumr.metrics.core import claim_process_registry
        self._claimed_sources: list[str] = []
        from tpumr.mapred import shuffle_copier as _sc  # registers hists
        from tpumr.mapred import tpu_runner as _tr
        _sc.shuffle_metrics()
        _tr.runner_metrics()
        for src in ("shuffle", "tpu"):
            reg = claim_process_registry(src, self.name)
            if reg is not None:
                self.metrics.register(reg)
                self._claimed_sources.append(src)
        #: shuffle merge-engine totals across this tracker's finished
        #: attempts (uniform /metrics surface for the in-memory merges,
        #: bounded-fan-in passes, and segment placement)
        self._merge_totals: dict[str, int] = {}
        self._mreg.set_gauge("shuffle_merge",
                             lambda: dict(self._merge_totals))
        # device-cache occupancy (ops/devcache.py): how much HBM the
        # side-input cache holds here and for which tag families — the
        # observability twin of the devcache_tags heartbeat inventory
        # the master's affinity placement consumes
        from tpumr.ops.devcache import occupancy as _devcache_occupancy
        self._mreg.set_gauge("devcache_entries",
                             lambda: _devcache_occupancy()["entries"])
        self._mreg.set_gauge("devcache_bytes",
                             lambda: _devcache_occupancy()["bytes"])
        self._mreg.set_gauge(
            "devcache_family_bytes",
            lambda: dict(_devcache_occupancy()["families"]))
        from tpumr.metrics import sinks_from_conf
        for sink in sinks_from_conf(conf):
            self.metrics.add_sink(sink)
        # distributed tracing (core/tracing.py): daemon-level tracer when
        # the TRACKER conf enables it (None otherwise — the fast path);
        # jobs traced without the daemon flag get a per-job tracer built
        # from their own conf, cached until job cleanup
        from tpumr.core.tracing import Tracer
        self.tracer = Tracer.from_conf(conf, "tasktracker")
        if self.tracer is not None:
            # ring-buffer drops = spans silently lost to backpressure;
            # invisible until surfaced as a gauge (satellite of PR 15)
            self._mreg.set_gauge("trace_spans_dropped",
                                 lambda: self.tracer.dropped)
        self._job_tracers: dict[str, Tracer] = {}
        # continuous profiler (metrics/sampler.py): None unless
        # tpumr.prof.enabled — trackers share the master's knobs so one
        # conf flips the whole cluster's sampling on
        from tpumr.metrics.sampler import StackSampler
        self.sampler = StackSampler.from_conf(conf, self.metrics)
        self._http: Any = None
        self._http_port = conf.get_int("mapred.task.tracker.http.port", -1)

        # self-checks ≈ NodeHealthCheckerService + TaskMemoryManagerThread
        from tpumr.mapred.node_health import (GLOBAL_MEMORY_MANAGER,
                                              NodeHealthChecker)
        script = conf.get("mapred.healthChecker.script.path")
        self.health: NodeHealthChecker | None = None
        if script:
            self.health = NodeHealthChecker(
                script,
                interval_s=conf.get_int("mapred.healthChecker.interval.ms",
                                        10_000) / 1000)
        self._memory_manager = (
            GLOBAL_MEMORY_MANAGER
            if conf.get_int("mapred.task.limit.maxrss.mb", 0) > 0 else None)

        # per-device accelerator quarantine: N consecutive device-classed
        # failures depool a physical device (its slot vanishes from the
        # next heartbeat); a background probe re-admits it. Conf-gated:
        # threshold 0 disables.
        from tpumr.mapred.node_health import TpuDeviceHealth
        dq_threshold = conf.get_int("tpumr.tpu.device.quarantine.failures",
                                    3)
        self.device_health: TpuDeviceHealth | None = None
        if self.max_tpu_map_slots > 0 and dq_threshold > 0:
            self.device_health = TpuDeviceHealth(
                self.n_tpu_devices, threshold=dq_threshold,
                probe_interval_s=conf.get_int(
                    "tpumr.tpu.device.probe.interval.ms", 10_000) / 1000,
                probe_max_interval_s=conf.get_int(
                    "tpumr.tpu.device.probe.max.interval.ms",
                    300_000) / 1000)
        self._mreg.set_gauge(
            "tpu_devices_quarantined",
            lambda: (len(self.device_health.quarantined())
                     if self.device_health is not None else 0))

        # hung-task reaping ≈ mapred.task.timeout + TaskTracker's
        # markUnresponsiveTasks: a monotonic last-progress stamp per
        # attempt, fed by the in-process reporter's observable activity
        # and by CHANGED umbilical status pushes (an isolated child's
        # unconditional 1 Hz push must not count — a hung child keeps
        # pushing identical payloads). The reaper thread fails attempts
        # silent past the (job-conf) timeout with failure_class=timeout.
        self._last_progress: dict[str, float] = {}
        self._progress_sigs: dict[str, tuple] = {}
        self._live_reporters: dict[str, Reporter] = {}
        #: last keepalive tick count pushed by each isolated child
        self._umb_ticks: dict[str, int] = {}
        self._reaper_thread = threading.Thread(
            target=self._reaper_loop, name=f"{self.name}-task-reaper",
            daemon=True)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "NodeRunner":
        if self.max_tpu_map_slots > 0:
            # durable XLA compiles across worker processes — the TPU-era
            # JvmManager-reuse analog (see parallel/jaxruntime.py)
            from tpumr.parallel.jaxruntime import configure_persistent_cache
            configure_persistent_cache(self.conf)
        self._server.start()
        self._hb_thread.start()
        self._reaper_thread.start()
        self.metrics.start()
        if self.sampler is not None:
            self.sampler.start()
        if self.health is not None:
            self.health.start()
        if self._memory_manager is not None:
            self._memory_manager.start()
        if self._http_port >= 0:
            from tpumr.http import StatusHttpServer, html_table
            srv = StatusHttpServer(self.name, port=self._http_port)
            srv.add_json("status", lambda q: self._status_dict())
            # /metrics + /json/metrics from one handler
            srv.attach_metrics(self.metrics)
            if self.sampler is not None:
                # /stacks?attempt= narrows to one in-process attempt's
                # thread (they run named task-<attempt_id>) — the live
                # complement to the post-mortem pstats block below
                self.sampler.attach_http(
                    srv, attempt_thread_prefix=lambda a: f"task-{a}")
            srv.add_json("profiles", lambda q: self.list_profiles())
            srv.add_json("profile",
                         lambda q: {"attempt": q["attempt"],
                                    "profile":
                                        self.get_profile(q["attempt"])},
                         parameterized=True)
            srv.add_json("tasklogs", lambda q: self.list_task_logs())
            srv.add_json("tasklog",
                         lambda q: {"attempt": q["attempt"],
                                    "log":
                                        self.get_task_log(q["attempt"])},
                         parameterized=True)

            from tpumr.http import RawHtml, html_escape

            def index_page(q: dict) -> str:
                st = self._status_dict()
                rows = [[RawHtml(
                            f"<a href='/task?attempt="
                            f"{html_escape(s['attempt_id'])}'>"
                            f"{html_escape(s['attempt_id'])}</a>"),
                         s["state"], s["phase"],
                         (f"tpu:{s['tpu_device_id']}" if s["run_on_tpu"]
                          else "cpu") if s["is_map"] else "reduce",
                         f"{s['progress']:.0%}"]
                        for s in st["task_statuses"]]
                profiled = self.list_profiles()
                prof_links = " · ".join(
                    f"<a href='/task?attempt={html_escape(a)}'>"
                    f"{html_escape(a)}</a>" for a in profiled)
                age = time.monotonic() - self._last_master_contact
                master_line = (
                    "<span class='bad'>master UNREACHABLE</span>"
                    if self.master_unreachable else
                    "<span class='ok'>master ok</span>")
                return (
                    f"<h1>TaskTracker {st['tracker_name']}</h1>"
                    f"<p>{master_line} · last contact {age:.1f}s ago</p>"
                    f"<p>host {st['host']} · cpu "
                    f"{st['count_cpu_map_tasks']}/{st['max_cpu_map_slots']}"
                    f" · tpu {st['count_tpu_map_tasks']}/"
                    f"{st['max_tpu_map_slots']} · reduce "
                    f"{st['count_reduce_tasks']}/{st['max_reduce_slots']}"
                    f" · devices free "
                    + "".join("●" if f else "○"
                              for f in st["available_tpu_devices"])
                    + "</p><h2>Running attempts</h2>"
                    + html_table(["attempt", "state", "phase", "backend",
                                  "progress"], rows)
                    + (f"<h2>Profiled attempts</h2><p>{prof_links}</p>"
                       if profiled else ""))

            def task_page(q: dict) -> str:
                """Per-attempt detail (≈ taskdetails.jsp + the
                TaskLogServlet links): live status when running, the
                retained child log link, and the cProfile report's
                top-N pstats lines inline instead of stranding
                profile.out in the task-local dir."""
                aid = q["attempt"]
                with self.lock:
                    st = self.running.get(aid)
                parts = [f"<h1>Attempt {html_escape(aid)}</h1>"]
                if st is not None:
                    parts.append(
                        f"<p>state <b>{html_escape(st.state)}</b> · phase "
                        f"{html_escape(st.phase)} · progress "
                        f"{st.progress:.0%}"
                        + (f" · diagnostics "
                           f"{html_escape(st.diagnostics)}"
                           if st.diagnostics else "") + "</p>")
                else:
                    parts.append("<p class='dim'>not currently running "
                                 "on this tracker</p>")
                if st is not None and st.counters:
                    # shuffle merge-engine placement for this attempt:
                    # in-memory merges, bounded passes, segment homes
                    from tpumr.core.counters import TaskCounter
                    fw = st.counters.get(TaskCounter.FRAMEWORK_GROUP) or {}
                    rows = [[html_escape(k.lower()), int(fw[k])]
                            for k in self._MERGE_COUNTER_KEYS if k in fw]
                    if rows:
                        parts.append("<h2>Shuffle / merge</h2>"
                                     + html_table(["counter", "value"],
                                                  rows))
                if st is not None and self.sampler is not None:
                    # live view while the attempt runs; the pstats block
                    # below only exists after it finishes
                    parts.append(
                        f"<p>live: <a href='/stacks?attempt="
                        f"{html_escape(aid)}'>sampled stacks</a> · "
                        f"<a href='/flame?attempt={html_escape(aid)}'>"
                        f"flame graph</a> (last 30s)</p>")
                from tpumr.mapred.profiler import profile_top_lines
                try:
                    text = self.get_profile(aid)
                except KeyError:
                    parts.append("<p class='dim'>no profile for this "
                                 "attempt (enable mapred.task.profile "
                                 "and the task-id range keys)</p>")
                else:
                    top = profile_top_lines(text)
                    parts.append(
                        "<h2>Profile (top of pstats report)</h2><pre>"
                        + html_escape("\n".join(top)) + "</pre>"
                        f"<p><a href='/json/profile?attempt="
                        f"{html_escape(aid)}'>full profile.out</a></p>")
                try:
                    self._open_userlog(aid, "child.log").close()
                except KeyError:
                    pass
                else:
                    parts.append(
                        f"<p><a href='/json/tasklog?attempt="
                        f"{html_escape(aid)}'>retained child log</a></p>")
                return "".join(parts)

            srv.add_page("index", index_page)
            srv.add_page("task", task_page, parameterized=True)
            self._http = srv.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.sampler is not None:
            self.sampler.stop()
        self.metrics.stop()
        from tpumr.metrics.core import release_process_registry
        for src in self._claimed_sources:
            release_process_registry(src, self.name)
        if self.tracer is not None:
            self.tracer.flush()
        with self.lock:
            tracers = list(self._job_tracers.values())
        for t in tracers:
            t.flush()
        if self.health is not None:
            self.health.stop()
        if self.device_health is not None:
            self.device_health.stop()
        if self._http is not None:
            self._http.stop()
        self._server.stop()
        shutil.rmtree(self.local_root, ignore_errors=True)

    @property
    def shuffle_port(self) -> int:
        return self._server.port

    # ------------------------------------------------------------ status

    def _slot_utilization(self) -> dict:
        """Busy fraction per slot pool (0.0 when the pool is absent —
        a present-but-zero series beats a missing one)."""
        with self.lock:
            cpu, tpu, red = self._counts()
        return {
            "cpu": cpu / self.max_cpu_map_slots
            if self.max_cpu_map_slots else 0.0,
            "tpu": tpu / self.max_tpu_map_slots
            if self.max_tpu_map_slots else 0.0,
            "reduce": red / self.max_reduce_slots
            if self.max_reduce_slots else 0.0,
        }

    def _counts(self) -> tuple[int, int, int]:
        cpu = tpu = red = 0
        for aid, st in self.running.items():
            if st.state != TaskState.RUNNING:
                continue
            if st.is_map:
                if st.run_on_tpu:
                    tpu += 1
                else:
                    cpu += 1
            else:
                red += 1
        return cpu, tpu, red

    def _available_tpu_devices(self) -> list[bool]:
        """free[i] derived from running task statuses each heartbeat
        (≈ TaskTrackerStatus.availableGPUDevices, :536-550), minus any
        quarantined devices — the scheduler derives assignable device
        ids from this list, so a sick device vanishes here first."""
        free = [True] * self.n_tpu_devices
        for st in self.running.values():
            if (st.state == TaskState.RUNNING and st.run_on_tpu
                    and 0 <= st.tpu_device_id < self.n_tpu_devices):
                free[st.tpu_device_id] = False
        if self.device_health is not None:
            for d in self.device_health.quarantined():
                if 0 <= d < self.n_tpu_devices:
                    free[d] = False
        return free

    @staticmethod
    def _fetch_batcher_stats() -> dict:
        """Device→host transfer coalescing effectiveness (fetch_batcher):
        fetches vs actual tunnel roundtrips — first-class observability
        for the cost the TPU data path is designed around."""
        from tpumr.mapred.fetch_batcher import shared_batcher
        b = shared_batcher()
        return {"fetches": b.fetches, "roundtrips": b.roundtrips,
                "coalesced": b.batched}

    def _devcache_tags(self) -> "list[str]":
        """Bounded, SORTED list of device-cache tags resident here —
        the heartbeat inventory behind the master's affinity placement.
        Sorted so an unchanged inventory is byte-identical across beats
        and the heartbeat delta encoder elides it; bounded
        (tpumr.devcache.heartbeat.tags, 0 disables) so a tag-heavy
        workload can't bloat every beat."""
        limit = confkeys.get_int(self.conf, "tpumr.devcache.heartbeat.tags")
        if limit <= 0:
            return []
        from tpumr.ops.devcache import inventory
        return sorted(inventory(max_tags=limit))

    def _status_dict(self) -> dict:
        with self.lock:
            cpu, tpu, red = self._counts()
            statuses = [st.to_dict() for st in self.running.values()]
            # memory accounting for the capacity scheduler's matching
            # (≈ CapacityTaskScheduler memory checks): total offered minus
            # the declared demand of everything running; -1 = unlimited
            total_mb = self.conf.get_int("mapred.tasktracker.memory.mb", -1)
            if total_mb >= 0:
                used = sum(t.memory_mb for aid, t in self.running_tasks.items()
                           if self.running.get(aid) is not None
                           and self.running[aid].state == TaskState.RUNNING)
                avail_mb = max(0, total_mb - used)
            else:
                avail_mb = -1
            # device quarantine shrinks the ADVERTISED TPU slot pool on
            # the next heartbeat (the acceptance contract: a sick device
            # is observably depooled, and restored when the probe clears)
            quarantined = (self.device_health.quarantined()
                           if self.device_health is not None else [])
            tpu_slots = max(0, self.max_tpu_map_slots - len(quarantined))
            return {
                "available_memory_mb": avail_mb,
                "fetch_failures": list(self._fetch_failures),
                "tracker_name": self.name,
                "host": self.host,
                "shuffle_addr": f"{self.bind_host}:{self.shuffle_port}",
                "shuffle_port": self.shuffle_port,
                "max_cpu_map_slots": self.max_cpu_map_slots,
                "max_tpu_map_slots": tpu_slots,
                "quarantined_tpu_devices": quarantined,
                "max_reduce_slots": self.max_reduce_slots,
                "count_cpu_map_tasks": cpu,
                "count_tpu_map_tasks": tpu,
                "count_reduce_tasks": red,
                "available_tpu_devices": self._available_tpu_devices(),
                "device_fetch": self._fetch_batcher_stats(),
                # bounded devcache inventory (tag names only — byte
                # counts stay in the local gauges): the master's
                # affinity placement signal. A baseline heartbeat key,
                # so steady-state beats delta-encode it away for free.
                "devcache_tags": self._devcache_tags(),
                "task_statuses": statuses,
                "rack": self.rack,
                "healthy": (self.health.healthy
                            if self.health is not None else True),
                "health_report": (self.health.report
                                  if self.health is not None else ""),
            }

    # ------------------------------------------------------------ heartbeat

    def _heartbeat_loop(self) -> None:
        import random as _random
        while not self._stop.is_set():
            wait_s = self.heartbeat_s
            try:
                if self.tracer is None:
                    self._heartbeat_once()
                else:
                    # daemon-scoped trace (trace id = the tracker, not a
                    # job): heartbeat latency is where master contention
                    # shows up first. The span's context rides the
                    # status dict so the master records its phase
                    # breakdown (fold/assign/deferred_io) as sub-spans
                    # of THIS span — one swimlane shows where a slow
                    # beat's time went, master-side included.
                    with self.tracer.span("heartbeat",
                                          f"daemon-{self.name}") as hb:
                        self._heartbeat_once(hb_span=hb)
            except (ConnectionError, OSError):
                # LOST MASTER: transport-level failure (crashed,
                # restarting, partitioned). In-flight tasks keep
                # running; retry with capped jittered exponential
                # backoff so a restarting master isn't stampeded by the
                # whole fleet at once. NOT a fault of this tracker and
                # NOT an application error — nothing is killed.
                self._master_failures += 1
                self.master_unreachable = True
                self._mreg.incr("master_unreachable_beats")
                backoff = min(self._lost_master_backoff_max_s,
                              self.heartbeat_s
                              * (2 ** min(self._master_failures, 6)))
                wait_s = max(self.heartbeat_s,
                             backoff * _random.uniform(0.5, 1.0))
            except Exception:
                # application-level RPC error: the master is ALIVE and
                # answered (a raise inside the handler, an auth refusal)
                # — keep the normal cadence, no lost-master backoff
                pass
            self._stop.wait(wait_s)

    def _metrics_piggyback(self) -> dict:
        """The compact metrics snapshot that rides every heartbeat:
        cumulative counters + cumulative sparse histogram state + numeric
        gauges, per source. Cumulative (not delta) on purpose — replayed
        heartbeats merge idempotently master-side (metrics/cluster.py).
        The tracker's own per-instance source name is normalized to
        ``tasktracker`` so cluster metric names don't embed instance
        names."""
        out: dict[str, dict] = {}
        for src, t in self.metrics.typed_snapshot().items():
            name = "tasktracker" if src == self.name else src
            counters = {k: v for k, v in (t.get("counters") or {}).items()
                        if isinstance(v, (int, float))}
            gauges = {k: v for k, v in (t.get("gauges") or {}).items()
                      if isinstance(v, (int, float, dict))}
            hists = t.get("histograms") or {}
            if counters or gauges or hists:
                out[name] = {"counters": counters, "gauges": gauges,
                             "histograms": hists}
        return out

    def _suppress_statuses(self, statuses: "list[dict]") -> "list[dict]":
        """The RUNNING-status rate limit: drop statuses whose
        (state, phase) is unchanged and whose last ship is fresher than
        the report interval. Terminal statuses always pass (losing one
        would lose the completion)."""
        if not self._status_interval_s:
            return statuses
        now = time.monotonic()
        out = []
        for sd in statuses:
            if sd["state"] != TaskState.RUNNING:
                out.append(sd)
                continue
            aid = sd["attempt_id"]
            key = (sd["state"], sd.get("phase"))
            prev = self._status_shipped.get(aid)
            if prev is not None and prev[:2] == key \
                    and now - prev[2] < self._status_interval_s:
                continue
            self._status_shipped[aid] = (*key, now)
            out.append(sd)
        return out

    def _heartbeat_once(self, hb_span: Any = None) -> None:
        full = self._status_dict()
        now = time.monotonic()
        metrics = None
        if now - self._piggyback_last >= self._piggyback_interval_s:
            try:
                metrics = self._metrics_piggyback()
            except Exception:  # noqa: BLE001 — metering must not break
                metrics = None  # the heartbeat lease
        # wire encoding: full on (re)contact, change-only delta after —
        # the encoder also omits an UNCHANGED metrics piggyback (it is
        # cumulative, so the master's last fold still holds). Delta
        # beats additionally rate-limit unchanged RUNNING statuses; a
        # FULL beat bypasses that (it resets the master's believed set)
        wire = full
        if self._hb_encoder.will_delta():
            wire = dict(full, task_statuses=self._suppress_statuses(
                full["task_statuses"]))
        status = self._hb_encoder.encode(wire, metrics)
        if hb_span is not None:
            # the master pops this and parents its heartbeat phase
            # sub-spans to it (never stored in the tracker registry)
            status["trace"] = hb_span.context
        cpu, tpu, red = (full["count_cpu_map_tasks"],
                         full["count_tpu_map_tasks"],
                         full["count_reduce_tasks"])
        # ask if ANY pool has room (TaskTracker.java:1841-1844)
        ask = (cpu < self.max_cpu_map_slots or tpu < self.max_tpu_map_slots
               or red < self.max_reduce_slots)
        try:
            resp = self.master.call("heartbeat", status,
                                    self._initial_contact,
                                    ask, self._response_id)
        except Exception:
            # delivery UNKNOWN (the master may have applied the beat and
            # lost the response): the next beat must re-ship the full
            # status — a delta against a baseline newer than ours could
            # mask a changed-then-reverted key forever
            self._hb_encoder.reset()
            raise
        self._hb_encoder.delivered()
        # re-contact: the lost-master state clears the moment a beat
        # lands (the master that answered has adopted our full status)
        self.master_unreachable = False
        self._master_failures = 0
        self._last_master_contact = time.monotonic()
        if metrics is not None:
            self._piggyback_last = now
        self._initial_contact = False
        self._response_id = resp["response_id"]
        # adaptive cadence: the master instructs the next interval
        # (scaled to fleet size, ≈ HeartbeatResponse.getHeartbeat-
        # Interval); the loop's _stop.wait reads heartbeat_s fresh
        # every beat, so the new cadence takes effect immediately
        nxt = resp.get("next_interval_ms")
        if isinstance(nxt, (int, float)) and nxt > 0:
            self.heartbeat_s = nxt / 1000.0
        if any(a.get("type") == "resend_full"
               for a in resp["actions"]):
            # the master did NOT fold this beat (no baseline — it wants
            # the full status first): keep every status and report for
            # the re-send, or a terminal completion delivered into the
            # early return would be dropped unseen and its task re-run
            for action in resp["actions"]:
                self._apply_action(action)
            return
        with self.lock:
            # the heartbeat DELIVERED these fetch-failure reports (they
            # were snapshotted into `full` first — a failed RPC keeps
            # them queued for the retry); entries appended since the
            # snapshot stay for the next beat
            sent_ff = len(full.get("fetch_failures", []))
            if sent_ff:
                del self._fetch_failures[:sent_ff]
            # Drop only statuses whose SENT snapshot was terminal — a task
            # that finished while the RPC was in flight was reported as
            # RUNNING, so it must survive until the next heartbeat or the
            # master never learns it completed.
            sent_terminal = {sd["attempt_id"]
                             for sd in full.get("task_statuses", [])
                             if sd["state"] in TaskState.TERMINAL}
            for aid in sent_terminal:
                self.running.pop(aid, None)
                self._status_shipped.pop(aid, None)
                self.running_tasks.pop(aid, None)
                # reaper bookkeeping dies with the attempt
                self._last_progress.pop(aid, None)
                self._progress_sigs.pop(aid, None)
                self._live_reporters.pop(aid, None)
                self._umb_ticks.pop(aid, None)
        for action in resp["actions"]:
            self._apply_action(action)
        self._hb_count += 1
        if self._hb_count % 20 == 0:
            self._cleanup_finished_jobs()

    def _cleanup_finished_jobs(self) -> None:
        """Drop map outputs + cached confs of terminal jobs (≈ the
        KillJobAction-driven purge of job-local dirs). Streamed-handoff
        entries (``handoff:<job>`` keys) are NOT governed by their
        job's terminal state — a finished upstream stage keeps serving
        its live pipeline — so they consult the master's purge oracle
        (pipeline terminal?) instead."""
        from tpumr.pipeline.handoff import SERVE_PREFIX
        with self.lock:
            # include resolver-populated token entries for jobs this
            # tracker never ran (shuffle-source role) so they stop
            # authenticating once the master reports the job terminal
            all_ids = ({j for j, _ in self.map_outputs}
                       | set(self.job_confs) | set(self._job_tokens))
        job_ids = {j for j in all_ids
                   if not j.startswith(SERVE_PREFIX)}
        for key in all_ids - job_ids:
            job_id = key[len(SERVE_PREFIX):]
            try:
                if not self.master.call("handoff_purgeable", job_id):
                    continue
            except Exception:  # noqa: BLE001 — master briefly down:
                continue       # keep serving, retry next sweep
            with self.lock:
                self.map_outputs = {k: v for k, v in
                                    self.map_outputs.items()
                                    if k[0] != key}
            self._spill_fds.invalidate(
                os.path.join(self.local_root, "handoff", job_id))
            shutil.rmtree(os.path.join(self.local_root, "handoff",
                                       job_id), ignore_errors=True)
            with self.lock:
                self._handoff_sources.pop(job_id, None)
        for job_id in job_ids:
            try:
                st = self.master.call("get_job_status", job_id)
            except Exception as e:  # noqa: BLE001
                from tpumr.ipc.rpc import RpcError
                if isinstance(e, RpcError) and "KeyError" in str(e):
                    # the master does not know this job at all (restart
                    # with recovery off, or past its alias horizon) —
                    # purgeable, or the outputs leak forever
                    st = {"state": "KILLED"}
                else:
                    continue
            if st["state"] in ("SUCCEEDED", "FAILED", "KILLED"):
                with self.lock:
                    self.map_outputs = {k: v for k, v in
                                        self.map_outputs.items()
                                        if k[0] != job_id}
                    jc = self.job_confs.pop(job_id, None)
                    self._job_tokens.pop(job_id, None)
                    jt = self._job_tracers.pop(job_id, None)
                    self._job_rebinds = {
                        k: v for k, v in self._job_rebinds.items()
                        if job_id not in (k, v)}
                if jt is not None:
                    jt.flush()   # stragglers of the finished traced job
                if jc is not None:
                    from tpumr.mapred import filecache
                    filecache.release_job(
                        jc, os.path.join(self.local_root, "cache"), job_id)
                self._spill_fds.invalidate(
                    os.path.join(self.local_root, job_id))
                shutil.rmtree(os.path.join(self.local_root, job_id),
                              ignore_errors=True)
        self._purge_old_userlogs()

    def _purge_old_userlogs(self) -> None:
        """Retained logs (profiles) age out after
        ``mapred.userlog.retain.hours`` (reference default 24) — they
        outlive job cleanup on purpose, but not forever."""
        logs = os.path.join(self.local_root, "userlogs")
        if not os.path.isdir(logs):
            return
        retain_s = self.conf.get_float("mapred.userlog.retain.hours",
                                       24.0) * 3600
        now = time.time()
        with self.lock:
            # a LIVE attempt's child.log lives in this tree; its job dir
            # must never age out mid-run (appends don't bump dir mtime)
            live_jobs = {str(TaskAttemptID.parse(aid).task.job)
                         for aid in self.running}
        for job_id in os.listdir(logs):
            if job_id in live_jobs:
                continue
            d = os.path.join(logs, job_id)
            try:
                # file mtimes are wall clock; so must the cutoff be
                if now - os.path.getmtime(d) > retain_s:  # tpulint: disable=clock-arith
                    shutil.rmtree(d, ignore_errors=True)
            except OSError:
                pass

    def _apply_action(self, action: dict) -> None:
        kind = action.get("type")
        if kind == "launch":
            task = Task.from_dict(action["task"])
            self._launch(action["job_id"], task)
        elif kind == "kill_task":
            with self.lock:
                self._kill_requested.add(action["attempt_id"])
        elif kind == "reinit":
            # ≈ ReinitTrackerAction: drop local state, re-register —
            # with a FULL status (the master that reset us has no
            # baseline to apply deltas to)
            with self.lock:
                self.running.clear()
                self.running_tasks.clear()
                self._initial_contact = True
                self._response_id = 0
                self._hb_encoder.reset()
                self._status_shipped.clear()
        elif kind == "resend_full":
            # the master lost our baseline (restart / eviction): the
            # next beat ships the FULL status and the master ADOPTS it.
            # Unlike reinit, nothing local is dropped — in-flight tasks
            # survive the master's restart.
            with self.lock:
                self._hb_encoder.reset()
                self._status_shipped.clear()
        elif kind == "recover_job":
            # a restarted master resubmitted an interrupted job under a
            # new id: re-key this tracker's served map outputs (and
            # translate future registrations) so reducers launched
            # under the NEW id can fetch outputs produced under the old
            old, new = str(action["old"]), str(action["new"])
            with self.lock:
                self._job_rebinds[old] = new
                for key in [k for k in self.map_outputs if k[0] == old]:
                    self.map_outputs[(new, key[1])] = \
                        self.map_outputs.pop(key)
        elif kind == "disallowed":
            # ≈ DisallowedTaskTrackerException: this host was excluded
            # (mapred.hosts/.exclude + mradmin -refreshNodes). The
            # reference's TaskTracker shuts down; ours stops
            # heartbeating and kills its local work — an operator must
            # re-admit the host before restarting the daemon.
            import logging
            logging.getLogger(__name__).warning(
                "master disallowed this tracker (host excluded) — "
                "shutting down")
            with self.lock:
                for aid in list(self.running_tasks):
                    self._kill_requested.add(aid)
            self._stop.set()

    # ------------------------------------------------------------ execution

    def _job_token(self, job_id: str) -> bytes:
        """This job's token, fetched from the master (cluster-secret
        channel) on first use and cached for the job's lifetime."""
        with self.lock:
            tok = self._job_tokens.get(job_id)
        if tok is None:
            tok = bytes(self.master.call("get_job_token", job_id) or b"")
            with self.lock:
                while len(self._job_tokens) >= 1024:
                    # hard cap (same policy as _job_token_misses): an
                    # evicted live job just re-resolves via the master
                    self._job_tokens.pop(next(iter(self._job_tokens)))
                self._job_tokens[job_id] = tok
        return tok

    def _job_token_or_none(self, scope: str) -> "bytes | None":
        """Token resolver for the RPC server: serve scoped callers of any
        job this tracker knows (it may be the shuffle SOURCE for a job
        whose reduce child runs elsewhere — resolve via the master on
        cache miss rather than rejecting). Unresolved scopes are
        negatively cached AND master lookups for unknown scopes are
        globally rate-limited, so a flood of unique bogus scopes (each a
        guaranteed cache miss) cannot amplify into unbounded
        tracker→master RPC traffic or memory growth."""
        now = time.monotonic()
        with self.lock:
            if self._job_token_misses.get(scope, 0) > now:
                return None
            if scope not in self._job_tokens:
                # token-bucket on miss lookups: ~4/s sustained, burst 20
                self._miss_budget = min(
                    20.0, self._miss_budget
                    + (now - self._miss_budget_ts) * 4.0)
                self._miss_budget_ts = now
                if self._miss_budget < 1.0:
                    return None
                self._miss_budget -= 1.0
        try:
            return self._job_token(scope) or None
        except Exception:  # noqa: BLE001 — unknown job / master down
            with self.lock:
                while len(self._job_token_misses) >= 1024:
                    # hard cap: evict oldest entries (insertion order)
                    self._job_token_misses.pop(
                        next(iter(self._job_token_misses)))
                self._job_token_misses[scope] = now + 30.0
            return None

    @staticmethod
    def _check_scope(job_id: str) -> None:
        """Token-scoped callers may only touch THEIR job (≈ the
        SecureShuffleUtils verification on MapOutputServlet)."""
        from tpumr.ipc.rpc import current_rpc_scope
        scope = current_rpc_scope()
        if scope is not None and scope != job_id:
            raise PermissionError(
                f"job token for {scope} cannot access job {job_id}")

    def _job_conf(self, job_id: str) -> JobConf:
        with self.lock:
            jc = self.job_confs.get(job_id)
        if jc is None:
            conf_dict = self.master.call("get_job_conf", job_id)
            jc = JobConf()
            for k, v in conf_dict.items():
                jc.set(k, v)
            # tracker-local cache root for DistributedCache localization
            jc.set("tpumr.cache.dir", os.path.join(self.local_root, "cache"))
            # shuffle spill dir (ShuffleCopier disk segments) — inside the
            # job scratch tree so job cleanup rmtree's any stragglers
            jc.set("tpumr.task.local.dir",
                   os.path.join(self.local_root, job_id, "shuffle"))
            jc.set("tpumr.job.id", job_id)
            # retained logs tree (≈ userlogs): per-attempt profiles land
            # here, OUTSIDE the job scratch dir that cleanup rmtree's
            jc.set("tpumr.task.userlogs.dir",
                   os.path.join(self.local_root, "userlogs", job_id))
            # pipeline streamed handoff: the tee spills land OUTSIDE the
            # job scratch tree — they must outlive this job's cleanup
            # (downstream stages fetch them after the job is terminal)
            # and are purged only once the owning pipeline is over.
            # Thread-isolated tasks only: a PROCESS child's registration
            # payload never reaches the tracker, so its tee would be
            # write-only waste — those stages serve via DFS fallback
            if jc.get_boolean("tpumr.pipeline.stream.handoff", False) \
                    and jc.get("tpumr.task.isolation",
                               "thread") != "process":
                jc.set("tpumr.pipeline.handoff.dir",
                       os.path.join(self.local_root, "handoff", job_id))
            # downstream streamed stage: stash the in-process stream-
            # source factory (MapLocator over the master's handoff feed
            # + this tracker's rpc credentials). Thread-isolated tasks
            # only — a process child's conf serializes to a file, and
            # its maps fall back to the committed DFS artifact instead.
            if jc.get("tpumr.pipeline.handoff.upstream") and \
                    jc.get("tpumr.task.isolation", "thread") != "process":
                jc.set("tpumr.pipeline.handoff.source",
                       self._handoff_source)
            # trace sink fallback: a client may enable tracing without
            # naming a dir (those are daemon-side keys) — without this,
            # the tracker's and child's spans would be silently dropped
            from tpumr.core.tracing import trace_dir_from_conf
            if trace_dir_from_conf(jc) is None:
                d = trace_dir_from_conf(self.conf)
                if d:
                    jc.set("tpumr.trace.dir", d)
            with self.lock:
                self.job_confs[job_id] = jc
        return jc

    def _launch(self, job_id: str, task: Task) -> None:
        aid = str(task.attempt_id)
        status = TaskStatus(attempt_id=task.attempt_id, is_map=task.is_map,
                            state=TaskState.RUNNING,
                            phase=TaskPhase.MAP if task.is_map
                            else TaskPhase.SHUFFLE,
                            run_on_tpu=task.run_on_tpu,
                            tpu_device_id=task.tpu_device_id)
        with self.lock:
            self.running[aid] = status
            self.running_tasks[aid] = task
            self._last_progress[aid] = time.monotonic()
        if not task.is_map:
            self._mreg.incr("reduces_launched")
        else:
            self._mreg.incr("tpu_maps_launched" if task.run_on_tpu
                            else "cpu_maps_launched")
        t = threading.Thread(target=self._run_task,
                             args=(job_id, task, status),
                             name=f"task-{aid}", daemon=True)
        t.start()

    def _trace_tracer(self, job_id: str, task: Task):
        """The tracer for a TRACED task (``task.trace`` stamped by the
        master), or None: the daemon's own when the tracker conf enables
        tracing, else a per-job tracer built from the job conf (cached
        until job cleanup). Never raises — a master outage during the
        conf fetch just runs the task untraced."""
        if task.trace is None:
            return None
        if self.tracer is not None:
            if self.tracer.trace_dir is None:
                # tracker conf enabled tracing but named no sink — the
                # job conf (dir-fallback-patched in _job_conf) supplies
                # it, exactly like the master patches its own at submit
                try:
                    from tpumr.core.tracing import trace_dir_from_conf
                    self.tracer.trace_dir = trace_dir_from_conf(
                        self._job_conf(job_id))
                except Exception:  # noqa: BLE001 — master briefly down
                    pass
            return self.tracer
        with self.lock:
            t = self._job_tracers.get(job_id)
        if t is not None:
            return t
        try:
            conf = self._job_conf(job_id)
        except Exception:  # noqa: BLE001
            return None
        from tpumr.core.tracing import Tracer
        t = Tracer.from_conf(conf, "tasktracker")
        if t is None:
            return None
        with self.lock:
            t = self._job_tracers.setdefault(job_id, t)
        return t

    def _run_task(self, job_id: str, task: Task, status: TaskStatus) -> None:
        aid = str(task.attempt_id)

        def killed() -> bool:
            with self.lock:
                return aid in self._kill_requested

        def on_progress(f: float) -> None:
            # in-process fraction reports land directly on the heartbeat
            # status (isolated children ship theirs over the umbilical) —
            # the master's per-TIP rate model is fed either way. Monotone
            # max: a late report must never roll back the settle's 1.0.
            status.progress = max(status.progress, min(1.0, float(f)))

        # cooperative cancellation: record loops poll this so a preemption
        # or speculative-race kill frees the slot mid-task, not at natural
        # completion (hard process kills arrive with the subprocess
        # executor; threads cannot be interrupted)
        reporter = Reporter(abort_check=killed, on_progress=on_progress)
        with self.lock:
            # the reaper samples this live reporter's counters/status for
            # progress liveness — zero hot-path cost (hoisted Counter
            # objects bypass Reporter.incr_counter, so a push-style hook
            # could never see the per-record activity anyway)
            self._live_reporters[aid] = reporter
        sem = (self._red_sem if not task.is_map
               else self._tpu_sem if task.run_on_tpu else self._cpu_sem)
        tracer = self._trace_tracer(job_id, task)
        wait_t0 = time.monotonic()
        sem.acquire()
        try:
            if tracer is None:
                self._run_task_inner(job_id, task, status, reporter)
                return
            self._run_task_traced(tracer, job_id, task, status, reporter,
                                  time.monotonic() - wait_t0)
        finally:
            sem.release()  # ≈ addFreeSlots on done/kill (:3401-3402)

    def _run_task_traced(self, tracer: Any, job_id: str, task: Task,
                         status: TaskStatus, reporter: Reporter,
                         slot_wait_s: float) -> None:
        """Traced execution: a tracker-role ``task:launch`` span parented
        to the master's scheduling span, and (in-process only — isolated
        children open their own) a task-role ``task:run`` span installed
        as the thread's ambient context so spill/merge/shuffle/TPU spans
        nest under it."""
        from tpumr.core import tracing
        aid = str(task.attempt_id)
        backend = ("tpu" if task.run_on_tpu else "cpu") if task.is_map \
            else "cpu"
        launch = tracer.start_span(
            "task:launch", task.trace["trace_id"], parent=task.trace,
            backend=backend, attempt_id=aid, tracker=self.name,
            is_map=task.is_map, slot_wait_s=round(slot_wait_s, 6))
        try:
            isolated = False
            try:
                isolated = self._isolate_in_process(
                    self._job_conf(job_id), task)
            except Exception:  # noqa: BLE001 — inner settles the failure
                pass
            # re-parent downstream spans (isolated child's task:run, the
            # master-facing chain stays schedule → launch → run)
            task.trace = launch.context
            if isolated:
                self._run_task_inner(job_id, task, status, reporter)
                return
            run = tracer.start_span("task:run", launch.trace_id,
                                    parent=launch, role="task",
                                    backend=backend, attempt_id=aid)
            try:
                with tracing.activate(tracer, run):
                    self._run_task_inner(job_id, task, status, reporter)
            finally:
                tracer.finish(run.set(state=status.state))
        finally:
            tracer.finish(launch.set(state=status.state))
            tracer.flush()

    def _isolate_in_process(self, conf: JobConf, task: Task) -> bool:
        """Process isolation gate (≈ which tasks get a child JVM): opt-in
        via ``tpumr.task.isolation=process`` (job conf first, tracker conf
        fallback). TPU tasks and device-shuffle gang reduces always stay
        in-process — they must share the tracker's JAX runtime, device
        mesh, and HBM split cache."""
        mode = conf.get("tpumr.task.isolation",
                        self.conf.get("tpumr.task.isolation", "thread"))
        if mode != "process" or task.run_on_tpu:
            return False
        if not task.is_map:
            from tpumr.mapred.device_shuffle import is_device_shuffle
            if is_device_shuffle(conf):
                return False
        return True

    def _abort_if_settled(self, status: TaskStatus) -> None:
        """A reaped (terminally settled) in-process attempt must never
        reach the commit gate or register map outputs: the master
        already counted it FAILED and re-queued the task, and a zombie
        can_commit call would CAPTURE the commit grant for a dead
        attempt — every re-run then loses the grant race and the task
        livelocks KILLED forever. Checked at the side-effect boundaries
        (output registration, commit)."""
        with self.lock:
            if status.state in TaskState.TERMINAL:
                raise TaskKilledError(
                    "attempt settled terminally while still running "
                    "(reaped for progress silence)")

    def _run_task_inner(self, job_id: str, task: Task, status: TaskStatus,
                        reporter: Reporter) -> None:
        aid = str(task.attempt_id)
        try:
            conf = self._job_conf(job_id)
            if self._isolate_in_process(conf, task):
                from tpumr.mapred.process_runner import run_task_in_process
                run_task_in_process(self, job_id, task, status, conf)
                return
            from tpumr.mapred.profiler import maybe_profile, profile_dir
            committed = True
            local_dir = os.path.join(self.local_root, job_id, aid)
            prof_dir = profile_dir(conf, aid, local_dir)
            if task.is_map:
                out = maybe_profile(
                    conf, task, prof_dir,
                    lambda: run_map_task(conf, task, local_dir, reporter,
                                         status=status))
                self._abort_if_settled(status)
                with self.lock:
                    if out[0]:
                        # stamp the producing attempt on the served index
                        # (fi serve seams target attempt generations; a
                        # re-run registers OVER the lost attempt's entry)
                        idx = dict(out[1])
                        idx["attempt"] = aid
                        idx["attempt_no"] = task.attempt_id.attempt
                        # total output size rides the success status into
                        # the completion event — the reduces' fetch-
                        # ordering key (size-aware shuffle)
                        status.output_bytes = sum(
                            int(p[2]) for p in idx.get("partitions", ()))
                        # a job recovered under a new id registers its
                        # stragglers' outputs under the NEW key
                        self.map_outputs[
                            (self._job_rebinds.get(job_id, job_id),
                             task.partition)] = (out[0], idx)
                # commit covers direct-output maps AND map-side named
                # outputs (lib.MultipleOutputs) in jobs with reducers;
                # needs_commit makes it a no-op when no files exist
                committed = self._commit(conf, task)
            else:
                status.phase = TaskPhase.SHUFFLE
                handoff_out = None
                from tpumr.mapred.device_shuffle import is_device_shuffle
                if is_device_shuffle(conf):
                    # gang task: exchange + sort on this host's mesh
                    from tpumr.mapred.device_shuffle import run_device_reduce
                    run_device_reduce(
                        conf, task,
                        self._remote_dense_fetch_factory(job_id, task),
                        reporter)
                else:
                    fetch = self._remote_fetch_factory(job_id, task)
                    handoff_out = maybe_profile(
                        conf, task, prof_dir,
                        lambda: run_reduce_task(conf, task, fetch,
                                                reporter))
                status.phase = TaskPhase.REDUCE
                self._abort_if_settled(status)
                committed = self._commit(conf, task)
                if handoff_out and not committed:
                    # the tee of a commit-race loser must not linger on
                    # disk (nothing would ever register or purge it)
                    try:
                        os.unlink(handoff_out["path"])
                    except OSError:
                        pass
                elif committed and handoff_out:
                    # streamed stage handoff: ONLY the commit winner
                    # registers (a speculative loser's tee must never
                    # serve) — downstream pipeline maps fetch this
                    # through the same get_map_output endpoints, keyed
                    # off the job id proper so job cleanup can't
                    # collide with the pipeline-scoped lifetime
                    from tpumr.pipeline.handoff import serve_key
                    idx = dict(handoff_out["index"])
                    idx["attempt"] = aid
                    idx["attempt_no"] = task.attempt_id.attempt
                    with self.lock:
                        self.map_outputs[
                            (serve_key(self._job_rebinds.get(job_id,
                                                             job_id)),
                             task.partition)] = (handoff_out["path"],
                                                 idx)
                    self._mreg.incr("handoff_outputs_registered")
            with self.lock:
                killed = aid in self._kill_requested
                # the reaper may have terminally settled this attempt
                # (FAILED/timeout) while the thread finished anyway — a
                # late settle must not resurrect it
                if status.state in TaskState.TERMINAL:
                    return
                status.counters = reporter.counters.to_dict()
                self._note_merge_counters(status.counters)
                status.progress = 1.0
                status.finish_time = time.time()
                if not committed:
                    status.diagnostics = "commit denied: another attempt won"
                    status.state = TaskState.KILLED
                else:
                    status.state = (TaskState.KILLED if killed
                                    else TaskState.SUCCEEDED)
            if status.state == TaskState.SUCCEEDED:
                self._note_device_result(task, None)
        except TaskKilledError:
            with self.lock:
                if status.state in TaskState.TERMINAL:
                    return  # reaped: FAILED/timeout already settled
                status.diagnostics = "attempt killed while running " \
                                     "(preempted or superseded)"
                status.finish_time = time.time()
                status.state = TaskState.KILLED  # requeue, no attempt budget
        except Exception as e:  # noqa: BLE001 — task failure is data
            from tpumr.mapred.task import classify_exception
            with self.lock:
                if status.state in TaskState.TERMINAL:
                    return
                status.diagnostics = f"{type(e).__name__}: {e}\n" + \
                    traceback.format_exc(limit=8)
                status.finish_time = time.time()
                # the demotion/quarantine signal: tagged at the failure
                # site (tpu_runner) or classified generically here
                status.failure_class = classify_exception(e)
                status.state = TaskState.FAILED
            self._note_device_result(task, status.failure_class)

    def _note_device_result(self, task: Task,
                            failure_class: "str | None") -> None:
        """Feed the per-device quarantine: device-classed failures of TPU
        attempts count against their physical device; a success (or any
        non-device failure) breaks the consecutive streak."""
        if (self.device_health is None or not task.is_map
                or not task.run_on_tpu or task.tpu_device_id < 0):
            return
        from tpumr.mapred.task import FailureClass
        dev = task.tpu_device_id % max(1, self.n_tpu_devices)
        if failure_class == FailureClass.DEVICE:
            if self.device_health.record_failure(dev):
                self._mreg.incr("tpu_device_quarantines")
        else:
            self.device_health.record_success(dev)

    # ------------------------------------------------------------ reaper
    # ≈ mapred.task.timeout + TaskTracker.markUnresponsiveTasks: fail
    # attempts that stopped reporting progress. Liveness is OBSERVED, not
    # pushed: the reaper samples each running attempt's progress
    # signature (phase, progress, status line, total counter ticks —
    # from the live in-process Reporter when there is one, else from the
    # umbilical-pushed status) and bumps last_progress on change. An
    # isolated child's unconditional 1 Hz status push therefore does NOT
    # count unless its payload changed, and neither does its kill-poll
    # ping — a hung child is reaped despite both threads staying alive.

    def _progress_signature(self, aid: str, st: TaskStatus,
                            reporter: "Reporter | None") -> tuple:
        if reporter is not None:
            total = sum(c.value for g in reporter.counters for c in g)
            note = reporter.status
            ticks = reporter.ticks
        else:
            total, note, ticks = 0, "", 0
        pushed = sum(v for g in (st.counters or {}).values()
                     for v in g.values()) if st.counters else 0
        with self.lock:
            umb_ticks = self._umb_ticks.get(aid, 0)
        return (st.phase, round(st.progress, 6), note, total, ticks,
                pushed, umb_ticks)

    def _task_timeout_s(self, aid: str) -> float:
        """This attempt's progress timeout (job conf wins over tracker
        conf, tracker conf over the Hadoop default; ≤0 disables —
        mapred.task.timeout contract)."""
        tracker_ms = confkeys.get_int(self.conf, "mapred.task.timeout")
        try:
            job_id = str(TaskAttemptID.parse(aid).task.job)
        except (ValueError, IndexError):
            return tracker_ms / 1000
        with self.lock:
            jc = self.job_confs.get(job_id)
        if jc is None:
            return tracker_ms / 1000
        return jc.get_int("mapred.task.timeout", tracker_ms) / 1000

    def _reaper_wait_s(self) -> float:
        """Poll granularity: a quarter of the SMALLEST live timeout
        (tracker conf and every cached job conf — a job may override
        mapred.task.timeout far below the tracker's), bounded [0.1, 5]s,
        so a tight per-job timeout is enforced near its configured
        value, not at a fixed 5 s grid."""
        smallest = confkeys.get_int(self.conf, "mapred.task.timeout")
        with self.lock:
            confs = list(self.job_confs.values())
        for jc in confs:
            t = jc.get_int("mapred.task.timeout", smallest)
            if 0 < t < smallest or smallest <= 0 < t:
                smallest = t
        if smallest <= 0:
            return 5.0   # reaping disabled everywhere; idle slowly
        return max(0.1, min(5.0, smallest / 1000 / 4.0))

    def _reaper_loop(self) -> None:
        while not self._stop.wait(self._reaper_wait_s()):
            try:
                self._reap_hung_tasks()
            except Exception:  # noqa: BLE001 — the reaper must outlive
                pass           # any one attempt's weirdness

    def _reap_hung_tasks(self) -> "list[str]":
        now = time.monotonic()
        with self.lock:
            snapshot = [(aid, st, self._live_reporters.get(aid))
                        for aid, st in self.running.items()
                        if st.state == TaskState.RUNNING]
        reaped = []
        for aid, st, reporter in snapshot:
            try:
                sig = self._progress_signature(aid, st, reporter)
            except RuntimeError:
                # a counter table grew mid-iteration (live Counters are
                # read lock-free) — a mutating table IS task activity
                with self.lock:
                    self._last_progress[aid] = now
                continue
            with self.lock:
                if self._progress_sigs.get(aid) != sig:
                    self._progress_sigs[aid] = sig
                    self._last_progress[aid] = now
                    continue
                last = self._last_progress.setdefault(aid, now)
            timeout_s = self._task_timeout_s(aid)
            if timeout_s <= 0 or now - last <= timeout_s:
                continue
            if self._reap_one(aid, now - last, timeout_s):
                reaped.append(aid)
        return reaped

    def _reap_one(self, aid: str, silent_s: float,
                  timeout_s: float) -> bool:
        """Terminally fail one silent attempt. The kill mechanics differ
        by isolation: the babysitter SIGKILLs an isolated child's whole
        session via _kill_tree the moment the kill request lands;
        in-process runners see the cooperative cancel flag at their next
        batch/record boundary (a thread cannot be interrupted — the
        settle guards keep a late finisher from resurrecting the
        attempt)."""
        with self.lock:
            st = self.running.get(aid)
            if st is None or st.state in TaskState.TERMINAL:
                return False
            self._kill_requested.add(aid)   # SIGKILL / cooperative cancel
            st.diagnostics = (
                f"Task {aid} failed to report status for "
                f"{silent_s:.0f} seconds (mapred.task.timeout="
                f"{int(timeout_s * 1000)} ms). Killing!")
            from tpumr.mapred.task import FailureClass
            st.failure_class = FailureClass.TIMEOUT
            st.finish_time = time.time()
            st.state = TaskState.FAILED
            task = self.running_tasks.get(aid)
        self._mreg.incr("tasks_reaped_timeout")
        if task is not None and task.trace is not None:
            try:
                job_id = str(TaskAttemptID.parse(aid).task.job)
                tracer = self._trace_tracer(job_id, task)
                if tracer is not None:
                    tracer.instant("task:reaped", task.trace["trace_id"],
                                   parent=task.trace, attempt_id=aid,
                                   silent_s=round(silent_s, 3))
            except Exception:  # noqa: BLE001 — observability best-effort
                pass
        return True

    #: framework counters rolled up into the /metrics shuffle_merge gauge
    _MERGE_COUNTER_KEYS = ("SHUFFLE_INMEM_MERGES",
                           "SHUFFLE_INMEM_MERGE_SEGMENTS",
                           "MERGE_PASSES", "MERGE_PASS_SEGMENTS",
                           "REDUCE_SHUFFLE_SEGMENTS_MEM",
                           "REDUCE_SHUFFLE_SEGMENTS_DISK")

    def _note_merge_counters(self, counters: "dict | None") -> None:
        """Fold one finished attempt's merge-engine counters into the
        tracker-wide totals behind the ``shuffle_merge`` metrics gauge."""
        if not counters:
            return
        from tpumr.core.counters import TaskCounter
        group = counters.get(TaskCounter.FRAMEWORK_GROUP) or {}
        with self.lock:   # RLock — safe from the umbilical path too
            for key in self._MERGE_COUNTER_KEYS:
                v = int(group.get(key, 0))
                if v:
                    k = key.lower()
                    self._merge_totals[k] = self._merge_totals.get(k, 0) + v

    def _commit(self, conf: JobConf, task: Task) -> bool:
        """Output promotion gated by the master (≈ COMMIT_PENDING →
        CommitTaskAction). Returns False when the grant went to another
        attempt — the caller must report this attempt KILLED, not SUCCEEDED
        (its output was discarded)."""
        from tpumr.core import tracing
        committer = FileOutputCommitter(conf)
        aid = str(task.attempt_id)
        if not committer.needs_commit(aid):
            return True
        with tracing.span("task:commit", attempt_id=aid) as s:
            if self.master.call("can_commit", str(task.task_id), aid):
                committer.commit_task(aid)
                return True
            if s is not None:
                s.set(denied=True)
            committer.abort_task(aid)
            return False

    # ------------------------------------------------------------ profiles
    # ≈ TaskLog.LogName.PROFILE served by TaskLogServlet: per-attempt
    # cProfile reports written by profiler.maybe_profile

    def _list_userlog_attempts(self, filename: str) -> "list[str]":
        """Attempts whose retained userlogs dir holds ``filename``."""
        logs = os.path.join(self.local_root, "userlogs")
        out = []
        if not os.path.isdir(logs):
            return out
        for job_id in sorted(os.listdir(logs)):
            job_dir = os.path.join(logs, job_id)
            if not os.path.isdir(job_dir):
                continue
            for aid in sorted(os.listdir(job_dir)):
                if os.path.exists(os.path.join(job_dir, aid, filename)):
                    out.append(aid)
        return out

    def _open_userlog(self, attempt_id: str, filename: str):
        """Open one attempt's retained file for reading, O(1) and
        symlink-proof. The id is round-tripped through the parser (which
        fully constrains the path — no traversal bytes survive it), and
        the file is opened O_NOFOLLOW: the attempt dir is chowned to the
        task user in setuid mode (_prepare_sandbox_for_user), so a job
        could swap child.log for a symlink and have the root-running
        tracker serve any file on the box (the native controller opens
        its logfile O_NOFOLLOW for the same reason)."""
        import re
        try:
            parsed = TaskAttemptID.parse(attempt_id)
        except (ValueError, IndexError):
            raise KeyError(f"bad attempt id {attempt_id!r}") from None
        if (str(parsed) != attempt_id
                or not re.fullmatch(r"[A-Za-z0-9-]+",
                                    parsed.task.job.cluster)):
            # the cluster segment is free-form text that survives the
            # parse/str roundtrip — without this check "../x" would too
            raise KeyError(f"bad attempt id {attempt_id!r}")
        path = os.path.join(self.local_root, "userlogs",
                            str(parsed.task.job), attempt_id, filename)
        try:
            fd = os.open(path, os.O_RDONLY | os.O_NOFOLLOW)
        except OSError as e:
            raise KeyError(
                f"no {filename} for attempt {attempt_id}: {e}") from None
        return os.fdopen(fd, "rb")

    def list_profiles(self) -> "list[str]":
        from tpumr.mapred.profiler import PROFILE_FILE
        return self._list_userlog_attempts(PROFILE_FILE)

    def get_profile(self, attempt_id: str) -> str:
        from tpumr.mapred.profiler import PROFILE_FILE
        with self._open_userlog(attempt_id, PROFILE_FILE) as f:
            return f.read().decode("utf-8", "replace")

    def list_task_logs(self) -> "list[str]":
        """Attempts with a retained child log (≈ the userlogs listing)."""
        return self._list_userlog_attempts("child.log")

    def get_task_log(self, attempt_id: str,
                     max_bytes: int = 1 << 20) -> str:
        """One attempt's retained stdout/stderr tail (≈ TaskLogServlet;
        tail-bounded like TaskLogsTruncater)."""
        with self._open_userlog(attempt_id, "child.log") as f:
            size = os.fstat(f.fileno()).st_size
            if size > max_bytes:
                f.seek(size - max_bytes)
            return f.read().decode("utf-8", "replace")

    # ------------------------------------------------------------ umbilical
    # child-process task protocol ≈ TaskUmbilicalProtocol (reference:
    # mapred/TaskUmbilicalProtocol.java:65) on the tracker's existing
    # authenticated RPC surface. The child NEVER talks to the master —
    # commit grants and completion events are proxied, like the reference
    # TaskTracker proxies commit/shuffle coordination for its children.

    def umbilical_ping(self, attempt_id: str) -> bool:
        """Kill-poll: True = the tracker wants this attempt gone."""
        self._check_scope(str(TaskAttemptID.parse(attempt_id).task.job))
        with self.lock:
            return attempt_id in self._kill_requested

    def umbilical_status(self, attempt_id: str, d: dict) -> bool:
        """Periodic progress/counter push (≈ statusUpdate). The reaper
        watches the fields written here: a push whose observable payload
        never changes keeps the attempt walking toward
        ``mapred.task.timeout``."""
        self._check_scope(str(TaskAttemptID.parse(attempt_id).task.job))
        with self.lock:
            st = self.running.get(attempt_id)
            if st is None or st.state in TaskState.TERMINAL:
                return False
            st.phase = d.get("phase", st.phase)
            st.progress = float(d.get("progress", st.progress))
            if d.get("counters"):
                st.counters = d["counters"]
            if "ticks" in d:
                self._umb_ticks[attempt_id] = int(d["ticks"])
            return True

    def umbilical_can_commit(self, task_id: str, attempt_id: str) -> bool:
        """Commit-grant proxy (≈ commitPending → JobTracker.canCommit)."""
        attempt = TaskAttemptID.parse(attempt_id)
        if str(TaskID.parse(task_id)) != str(attempt.task):
            # task_id must be the ATTEMPT's OWN task: the master's
            # can_commit setdefaults the grant to the first claimant, so
            # any laxer binding lets an attempt seed a sibling (or
            # foreign) task's grant with an attempt that never fails —
            # permanently denying that task's real attempts
            raise PermissionError(
                f"task {task_id} does not belong to attempt {attempt_id}")
        self._check_scope(str(attempt.task.job))
        return bool(self.master.call("can_commit", task_id, attempt_id))

    def umbilical_events(self, job_id: str, cursor: int) -> list:
        """Map-completion-event proxy for isolated reduce children."""
        self._check_scope(job_id)
        return self.master.call("get_map_completion_events", job_id, cursor)

    def umbilical_done(self, attempt_id: str, final: dict, job_id: str,
                       partition: int, out_path: str, index: dict) -> None:
        """Terminal report (≈ done): settle status, register map output."""
        if str(TaskAttemptID.parse(attempt_id).task.job) != job_id:
            # scope pins to job_id below — the attempt must actually BE
            # that job's, or a scoped caller could settle another job's
            # attempt by mislabeling the job argument
            raise PermissionError(
                f"attempt {attempt_id} does not belong to job {job_id}")
        self._check_scope(job_id)
        with self.lock:
            st = self.running.get(attempt_id)
            if st is not None and st.state not in TaskState.TERMINAL:
                st.counters = final.get("counters", {})
                self._note_merge_counters(st.counters)
                st.progress = float(final.get("progress", 1.0))
                st.phase = final.get("phase", st.phase)
                st.diagnostics = final.get("diagnostics", "")
                st.finish_time = time.time()
                st.state = final.get("state", TaskState.SUCCEEDED)
                if out_path and index:
                    # size-aware shuffle: isolated children report their
                    # output size exactly like in-process attempts do
                    st.output_bytes = sum(
                        int(p[2]) for p in index.get("partitions", ()))
            if out_path:
                # confine served paths to this tracker's scratch tree — the
                # shuffle server must never be steerable at arbitrary files
                real = os.path.realpath(out_path)
                root = os.path.realpath(self.local_root) + os.sep
                if real.startswith(root):
                    idx = dict(index)
                    idx["attempt"] = attempt_id
                    idx["attempt_no"] = TaskAttemptID.parse(
                        attempt_id).attempt
                    self.map_outputs[
                        (self._job_rebinds.get(job_id, job_id),
                         partition)] = (real, idx)

    def umbilical_fail(self, attempt_id: str, state: str,
                       diagnostics: str, failure_class: str = "") -> None:
        """Failure/kill report (≈ fsError/fatalError). ``failure_class``
        carries the child-side classification (task.FailureClass) into
        the heartbeat so the master's demotion/quarantine logic sees
        isolated attempts exactly like in-process ones."""
        self._check_scope(str(TaskAttemptID.parse(attempt_id).task.job))
        with self.lock:
            st = self.running.get(attempt_id)
            if st is not None and st.state not in TaskState.TERMINAL:
                st.diagnostics = diagnostics
                st.finish_time = time.time()
                st.failure_class = str(failure_class or "")
                st.state = (state if state in TaskState.TERMINAL
                            else TaskState.FAILED)

    # ------------------------------------------------- fetch failures

    def report_fetch_failure(self, reduce_attempt: str,
                             map_attempt: str) -> None:
        """A reduce on this tracker could not fetch ``map_attempt``'s
        output (≈ ReduceTask's fetch-failure notification up the
        umbilical): queue the report for the next heartbeat — the master
        counts distinct reducers per map attempt and re-executes the map
        at ``mapred.max.fetch.failures.per.map``. The reduce stays alive
        (stalled-but-progressing) while that happens."""
        if not map_attempt:
            return   # location never resolved — nothing to indict
        with self.lock:
            self._fetch_failures.append({"reduce_attempt": reduce_attempt,
                                         "map_attempt": map_attempt})
        self._mreg.incr("fetch_failures_reported")

    def umbilical_report_fetch_failure(self, reduce_attempt: str,
                                       map_attempt: str) -> None:
        """Child-process seam for :meth:`report_fetch_failure`. BOTH
        attempts must belong to the caller's job: a job-token child may
        only ever indict its own job's map outputs (the master
        additionally verifies the reducer is a real, running attempt)."""
        reduce_job = str(TaskAttemptID.parse(reduce_attempt).task.job)
        if map_attempt and \
                str(TaskAttemptID.parse(map_attempt).task.job) != reduce_job:
            raise PermissionError(
                f"map attempt {map_attempt} does not belong to "
                f"{reduce_attempt}'s job")
        self._check_scope(reduce_job)
        self.report_fetch_failure(reduce_attempt, map_attempt)

    # ------------------------------------------------------------ shuffle

    def _maybe_fail_serve(self, job_id: str, map_index: int,
                          index: dict) -> None:
        """Deterministic chaos seam on the serving side of the shuffle
        (the map-output-unfetchable failure mode: disk loss, corrupt
        spill, wedged-but-heartbeating tracker). Qualified points let a
        test target one map's output or one attempt GENERATION — e.g.
        ``tpumr.fi.shuffle.serve.a0.probability=1`` makes every map's
        FIRST attempt unfetchable while its re-run serves fine."""
        from tpumr.utils.fi import maybe_fail
        with self.lock:
            conf = self.job_confs.get(job_id)
        conf = conf if conf is not None else self.conf
        maybe_fail("shuffle.serve", conf)
        maybe_fail(f"shuffle.serve.m{map_index}", conf)
        attempt_no = index.get("attempt_no")
        if attempt_no is not None:
            maybe_fail(f"shuffle.serve.a{attempt_no}", conf)

    def _map_output_entry(self, job_id: str,
                          map_index: int) -> "tuple | None":
        """Served-output lookup that follows the recover_job rebinding
        in BOTH directions: entries are re-keyed to the NEW job id when
        the master teaches the rebinding, but reducers ADOPTED across
        the restart keep fetching with the OLD id — both must hit.
        Streamed-handoff keys (``handoff:<job>``) follow the SAME
        rebinding on their embedded job id: downstream pipeline splits
        name the pre-restart upstream id forever, while re-run reduces
        register under the recovered one."""
        from tpumr.pipeline.handoff import SERVE_PREFIX
        rebind = job_id
        if job_id.startswith(SERVE_PREFIX):
            inner = self._job_rebinds.get(job_id[len(SERVE_PREFIX):])
            if inner is not None:
                rebind = SERVE_PREFIX + inner
        with self.lock:
            ent = self.map_outputs.get((job_id, map_index))
            if ent is None and rebind != job_id:
                ent = self.map_outputs.get((rebind, map_index))
            if ent is None:
                new = self._job_rebinds.get(job_id)
                if new is not None:
                    ent = self.map_outputs.get((new, map_index))
        return ent

    def get_map_output(self, job_id: str, map_index: int,
                       partition: int) -> dict:
        """Serve one partition segment (≈ MapOutputServlet,
        TaskTracker.java:4050): raw length-prefixed (possibly compressed)
        bytes straight off the spill file + the codec name."""
        self._check_scope(job_id)
        ent = self._map_output_entry(job_id, map_index)
        if ent is None:
            raise KeyError(f"no map output for {job_id} map {map_index}")
        path, index = ent
        self._maybe_fail_serve(job_id, map_index, index)
        if index.get("dense"):
            raise ValueError(f"map output for {job_id} map {map_index} is "
                             "dense (device-shuffled job) — fetch with "
                             "get_map_output_dense")
        with open(path, "rb") as f:
            data = ifile.partition_bytes(f, index, partition)
        return {"data": data, "codec": index.get("codec", "none")}

    #: server-side cap on one chunk response — bounds tracker memory per
    #: request no matter what the client asks for (the chunked-transfer
    #: half of Missing #6: whole segments never ride one RPC response)
    MAX_CHUNK_BYTES = 4 << 20

    def get_map_output_chunk(self, job_id: str, map_index: int,
                             partition: int, offset: int,
                             max_bytes: int, wire: str = "none") -> dict:
        """Serve one bounded range of a partition segment's compressed
        payload (the streaming re-design of MapOutputServlet,
        TaskTracker.java:4050 — the reference streams via servlet chunked
        output; here each RPC response is one bounded chunk). ``offset``
        is payload-relative; ``total`` is the payload length so the copier
        knows when it has everything; ``raw`` is the decompressed size the
        ShuffleRamManager budgets on. ``wire`` (optional, 6th param so
        old 5-arg callers are untouched) names a codec the CLIENT can
        decode: chunks of uncompressed spills come back wire-compressed
        (response field ``wire``) when it shrinks them, with ``n`` the
        payload-space length covered so offsets stay payload-relative."""
        self._check_scope(job_id)
        path, index = self._chunk_entry(job_id, map_index)
        return serve_chunk(self._spill_fds, path, index, partition,
                           offset, max_bytes, self.MAX_CHUNK_BYTES, wire)

    def get_map_outputs_batch(self, job_id: str, partition: int,
                              map_indexes: "list[int]",
                              max_bytes_each: int = 1 << 20,
                              max_total_bytes: int = 8 << 20,
                              wire: str = "none") -> "list[dict]":
        """Batched multi-segment fetch: many (small) map outputs of one
        partition in ONE response frame — see :func:`serve_batch` for
        the per-entry failure / budget-omission / prefix-continuation
        contract. The per-entry fault seam fires INSIDE the batch, so a
        chaos-killed map fails its own entry while siblings land."""
        self._check_scope(job_id)

        def lookup(m: int) -> tuple:
            return self._chunk_entry(job_id, m)

        return serve_batch(
            self._spill_fds, lookup, partition, list(map_indexes),
            min(int(max_bytes_each), self.MAX_CHUNK_BYTES),
            min(int(max_total_bytes), 8 * self.MAX_CHUNK_BYTES),
            self.MAX_CHUNK_BYTES, wire)

    def _chunk_entry(self, job_id: str, map_index: int) -> tuple:
        """(path, index) of one chunk-servable output, with the lookup
        failure + chaos seam + dense guard shared by the chunk and
        batch endpoints."""
        ent = self._map_output_entry(job_id, map_index)
        if ent is None:
            raise KeyError(f"no map output for {job_id} map {map_index}")
        path, index = ent
        self._maybe_fail_serve(job_id, map_index, index)
        if index.get("dense"):
            raise ValueError(f"map output for {job_id} map {map_index} is "
                             "dense (device-shuffled job) — fetch with "
                             "get_map_output_dense")
        return path, index

    def get_map_output_dense(self, job_id: str, map_index: int) -> dict:
        """Serve a device-shuffled job's whole dense map output (same
        MapOutputServlet role; the exchange itself happens on the mesh).
        Ships the self-describing file verbatim — no parse/reserialize."""
        self._check_scope(job_id)
        ent = self._map_output_entry(job_id, map_index)
        if ent is None:
            raise KeyError(f"no map output for {job_id} map {map_index}")
        path, index = ent
        if not index.get("dense"):
            raise ValueError(f"map output for {job_id} map {map_index} is "
                             "not dense — fetch with get_map_output")
        with open(path, "rb") as f:
            return {"data": f.read()}

    def _map_locator(self, job_id: str):
        """Resolve a map's serving tracker from the master's completion
        events (shared by the IFile and dense fetch paths): returns
        ``locate(map_index) -> RpcClient`` to the source tracker."""
        return make_map_locator(
            lambda cursor: self.master.call("get_map_completion_events",
                                            job_id, cursor),
            self._rpc_secret,
            poll_s=self.conf.get_int("tpumr.shuffle.poll.ms", 200) / 1000.0,
            timeout_s=self.conf.get_int("tpumr.shuffle.timeout.ms",
                                        600_000) / 1000.0,
            conns_per_target=confkeys.get_int(
                self.conf, "tpumr.shuffle.conns.per.target"))

    def _handoff_source(self, upstream_job: str):
        """Shared per-upstream-stage stream source for downstream
        pipeline maps (the `tpumr.pipeline.handoff.source` conf seam):
        the PR-1 MapLocator over the master's HANDOFF completion-event
        feed, authenticated with this tracker's credentials. Cached —
        every map of the downstream stage on this tracker folds one
        cursor instead of N."""
        with self.lock:
            src = self._handoff_sources.get(upstream_job)
        if src is not None:
            return src
        from tpumr.pipeline.handoff import make_handoff_source
        src = make_handoff_source(
            upstream_job,
            lambda cursor: self.master.call(
                "get_handoff_completion_events", upstream_job, cursor),
            self._rpc_secret,
            poll_s=self.conf.get_int("tpumr.shuffle.poll.ms",
                                     200) / 1000.0)
        with self.lock:
            return self._handoff_sources.setdefault(upstream_job, src)

    def _remote_fetch_factory(self, job_id: str, task: Task):
        """Chunked shuffle source ≈ ReduceCopier.MapOutputCopier: resolves
        map locations from completion events; run_reduce_task drives it
        with the parallel RAM-budgeted ShuffleCopier."""
        from tpumr.mapred.shuffle_copier import RemoteChunkSource
        src = RemoteChunkSource(self._job_conf(job_id), job_id,
                                self._map_locator(job_id))
        reduce_attempt = str(task.attempt_id)
        src.on_fetch_failure = (
            lambda map_index, map_attempt:
            self.report_fetch_failure(reduce_attempt, map_attempt))
        return src

    def _remote_dense_fetch_factory(self, job_id: str, task: Task):
        """Dense fetch for device-shuffled jobs: pulls each map's whole
        fixed-width output (same serving seam, array payload)."""
        from tpumr.mapred.device_shuffle import parse_dense_bytes

        locate = self._map_locator(job_id)

        def fetch(map_index: int):
            out = locate(map_index).call("get_map_output_dense", job_id,
                                         map_index)
            return parse_dense_bytes(out["data"])

        return fetch
