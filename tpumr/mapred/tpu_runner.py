"""TPU map runner — stages the whole split into device memory and executes
the mapper as a JAX/XLA/Pallas program.

Replaces the reference's GPU pipes data path end to end:

- ``PipesGPUMapRunner`` (mapred/pipes/PipesGPUMapRunner.java:40-118) forked
  the *GPU* executable and streamed the split record-by-record over a socket
  (the MAP_ITEM hot loop :97-107) → here the split becomes ONE staged batch
  (DenseBatch via the input format's ``read_batch``, or a RecordBatch built
  from the record reader) and the kernel mapper consumes it whole.
- ``Application`` appended GPUDeviceId to argv so the CUDA child could
  ``cudaSetDevice`` (mapred/pipes/Application.java:162-181) → here
  ``task.tpu_device_id`` selects the ``jax.Device`` the batch is put on.
- Output returns pre-aggregated (kernels combine on device), entering the
  normal MapOutputBuffer → sort/spill → shuffle pipeline.

Selected by ``run_map_task`` when ``task.run_on_tpu`` is set — the same seam
where the reference picks the GPU runner (mapred/MapTask.java:433-438).
"""

from __future__ import annotations

import time
from typing import Any

import threading
from collections import OrderedDict

import numpy as np

from tpumr.core.counters import BackendCounter, TaskCounter
from tpumr.io.recordbatch import DenseBatch, RecordBatch
from tpumr.io.writable import serialize
from tpumr.mapred.api import MapRunnable
from tpumr.mapred.split import DenseSplit, InputSplit
from tpumr.utils import progress
from tpumr.utils.reflection import new_instance


class HbmSplitCache:
    """LRU cache of device-resident staged splits.

    New capability beyond the reference: iterative jobs (K-Means rounds,
    repeated scans) re-read their InputSplits from storage every round in
    MapReduce; here a split staged into HBM stays resident across tasks of
    the same process, so later rounds skip both storage I/O and the
    host→device transfer — the dominant cost off-host. Keyed by the split's
    identity (path, row range, dtype); bounded by bytes with LRU eviction.
    """

    def __init__(self, capacity_bytes: int) -> None:
        self.capacity = capacity_bytes
        self._entries: "OrderedDict[tuple, Any]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple) -> Any | None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return entry[0]
            self.misses += 1
            return None

    def put(self, key: tuple, value: Any, nbytes: int) -> None:
        with self._lock:
            if key in self._entries or nbytes > self.capacity:
                return  # oversized items never evict resident ones
            while self._bytes + nbytes > self.capacity and self._entries:
                # entries carry their CHARGED size: eviction accounting
                # must not depend on any particular value shape (split
                # tuples and device-output dicts share this cache)
                _, (_old, old_bytes) = self._entries.popitem(last=False)
                self._bytes -= old_bytes
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def drop_where(self, pred) -> None:
        """Evict every entry whose KEY satisfies ``pred`` (targeted
        invalidation — e.g. one side-input family of the ops devcache)."""
        with self._lock:
            for k in [k for k in self._entries if pred(k)]:
                _v, b = self._entries.pop(k)
                self._bytes -= b

    def snapshot(self) -> "list[tuple[tuple, int]]":
        """Locked point-in-time (key, charged_bytes) listing, LRU→MRU —
        the devcache inventory the tracker piggybacks on heartbeats.
        Values are deliberately NOT exposed (device arrays stay put)."""
        with self._lock:
            return [(k, b) for k, (_v, b) in self._entries.items()]

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes


_split_caches: dict[str, HbmSplitCache] = {}
_cache_lock = threading.Lock()


def runner_metrics():
    """The process-wide ``tpu`` metrics source: stage (host→device) and
    execute wall-time distributions for the device path, the CPU batch
    runner's twin, and a ``tpu_observed_acceleration`` gauge — measured
    mean CPU-batch time over mean TPU-execute time, sitting next to the
    per-job PROFILED factor the scheduler derives from whole-task
    runtimes (job status ``acceleration_factor``). The two disagreeing
    is signal: profiled includes staging + per-task overhead, observed
    is pure kernel wall time."""
    from tpumr.metrics.core import process_registry
    reg = process_registry("tpu")
    reg.histogram("tpu_stage_seconds")
    execute = reg.histogram("tpu_execute_seconds")
    cpu = reg.histogram("tpu_cpu_batch_seconds")

    def _observed() -> float:
        if not execute.count or not cpu.count:
            return 0.0
        tpu_mean = execute.sum / execute.count
        cpu_mean = cpu.sum / cpu.count
        return cpu_mean / tpu_mean if tpu_mean > 0 else 0.0

    reg.set_gauge("tpu_observed_acceleration", _observed)
    return reg

#: (kernel, input signature) pairs this process has dispatched before —
#: the trace's compile-cache attribute: a first dispatch ("cold") pays
#: XLA compilation or a persistent-cache load (parallel/jaxruntime.py);
#: later dispatches of the same signature hit the in-process jit cache
_dispatched: set = set()
_dispatched_lock = threading.Lock()


def _dispatch_signature(kernel_name: str, batch: Any) -> tuple:
    values = getattr(batch, "values", None)
    shape = tuple(getattr(values, "shape", ()) or ())
    dtype = str(getattr(values, "dtype", ""))
    return (kernel_name, shape, dtype)


def _compile_temperature(kernel_name: str, batch: Any) -> str:
    """'cold' before this process's first SUCCESSFUL dispatch of
    (kernel, signature) — XLA compiles or loads the persistent cache —
    else 'warm'. Mark with :func:`_mark_dispatched` only after the
    execution completes: a failed cold attempt's retry pays the compile
    again and must not report warm."""
    with _dispatched_lock:
        return ("warm" if _dispatch_signature(kernel_name, batch)
                in _dispatched else "cold")


def _mark_dispatched(kernel_name: str, batch: Any) -> None:
    with _dispatched_lock:
        _dispatched.add(_dispatch_signature(kernel_name, batch))


def split_cache(device: Any, capacity_bytes: int) -> HbmSplitCache:
    key = str(device)
    with _cache_lock:
        c = _split_caches.get(key)
        if c is None:
            c = _split_caches[key] = HbmSplitCache(capacity_bytes)
        c.capacity = capacity_bytes
        return c


def clear_split_caches() -> None:
    with _cache_lock:
        for c in _split_caches.values():
            c.clear()
        _split_caches.clear()


def _maybe_fail_accelerator(conf, dev_id: int) -> None:
    """Chaos seams for the accelerator fault-tolerance layer, classed so
    the demotion/quarantine pipeline sees exactly what a real fault
    would report: ``tpu.compile`` (failure_class=compile), ``tpu.execute``
    and the device-qualified ``tpu.execute.d<id>`` (failure_class=device
    — the qualified point lets a test sicken ONE physical device while
    its siblings keep serving)."""
    from tpumr.mapred.task import FailureClass
    from tpumr.utils.fi import maybe_fail
    maybe_fail("tpu.compile", conf, failure_class=FailureClass.COMPILE)
    maybe_fail("tpu.execute", conf, failure_class=FailureClass.DEVICE)
    if dev_id >= 0:
        maybe_fail(f"tpu.execute.d{dev_id}", conf,
                   failure_class=FailureClass.DEVICE)


class TpuMapRunner(MapRunnable):
    def configure(self, conf) -> None:
        self.conf = conf

    def run(self, reader, output, reporter, task_ctx=None) -> None:
        import jax
        from tpumr.ops import get_kernel
        from tpumr.parallel.jaxruntime import configure_persistent_cache

        conf = self.conf
        configure_persistent_cache(conf)
        _maybe_fail_accelerator(
            conf, getattr(task_ctx, "tpu_device_id", -1) if task_ctx else -1)
        name = conf.get_map_kernel()
        if not name:
            raise ValueError(
                "task placed on TPU but no kernel mapper configured "
                "(JobConf.set_map_kernel) — the scheduler should not place "
                "kernel-less jobs on TPU (JobQueueTaskScheduler.java:342-347 "
                "semantics)")
        kernel = get_kernel(name)

        # a windowed prelaunch (prelaunch_device_maps) already staged,
        # dispatched, and fetched this task's kernel output as part of a
        # many-task batched transfer — only the drain remains
        from tpumr.core import tracing

        mreg = runner_metrics()
        pre = getattr(task_ctx, "_device_prefetch", None) if task_ctx else None
        if pre is not None:
            if pre.device_rows is not None:
                from tpumr.mapred import device_output
                device_output.offer(
                    str(conf.get("tpumr.task.attempt.id", "")),
                    pre.device_rows)
            reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                  TaskCounter.MAP_INPUT_RECORDS,
                                  pre.num_records)
            reporter.incr_counter(BackendCounter.GROUP,
                                  BackendCounter.TPU_DEVICE_BYTES_STAGED,
                                  pre.staged_bytes)
            t0 = time.monotonic()
            with tracing.span("tpu:window_drain", backend="tpu",
                              records=pre.num_records,
                              staged_bytes=pre.staged_bytes):
                with mreg.histogram("tpu_window_drain_seconds").time():
                    for key, value in kernel.map_batch_drain(pre.fetched,
                                                             conf,
                                                             task_ctx):
                        output.collect(key, value)
            reporter.set_status(
                f"kernel {name} (pipelined window): {pre.num_records} "
                f"records, drained in {time.monotonic() - t0:.3f}s")
            return

        # device binding ≈ GPUDeviceId → cudaSetDevice
        dev_id = getattr(task_ctx, "tpu_device_id", -1) if task_ctx else -1
        device = _select_device(dev_id)

        with tracing.span("tpu:stage", backend="tpu",
                          device=str(device)) as st:
            try:
                with mreg.histogram("tpu_stage_seconds").time():
                    batch, counted_by_reader, staged_bytes = stage_batch(
                        self.conf, reader, task_ctx, device)
            except Exception as e:  # noqa: BLE001 — classify at the site
                from tpumr.mapred.task import (classify_accelerator_exception,
                                               tag_failure)
                raise tag_failure(e, classify_accelerator_exception(e))
            if st is not None:
                # staged_bytes == 0 means the split was already device-
                # resident (HBM split cache / output chain) — the stage
                # cost this span exists to surface was skipped entirely
                st.set(staged_bytes=staged_bytes,
                       hbm_cache="hit" if staged_bytes == 0 else "miss",
                       records=getattr(batch, "num_records", 0))
        if not counted_by_reader:
            # the record-reader path already counts MAP_INPUT_RECORDS
            reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                  TaskCounter.MAP_INPUT_RECORDS,
                                  getattr(batch, "num_records", 0))
        reporter.incr_counter(BackendCounter.GROUP,
                              BackendCounter.TPU_DEVICE_BYTES_STAGED,
                              staged_bytes)

        t0 = time.monotonic()
        temperature = _compile_temperature(name, batch)
        try:
            with mreg.histogram("tpu_execute_seconds").time(), \
                    jax.default_device(device):
                with tracing.span("tpu:execute", backend="tpu",
                                  kernel=name, device=str(device)) as ex:
                    if ex is not None:
                        ex.set(compile=temperature)
                    state = (kernel.map_batch_launch(batch, conf, task_ctx)
                             if type(kernel).supports_launch() else None)
                    if state is not None:
                        _offer_device_rows(kernel, state, conf)
                        # coalesce this task's device→host transfer with
                        # any concurrently-fetching TPU-slot threads: one
                        # tunnel roundtrip can carry many tasks' outputs
                        from tpumr.mapred.fetch_batcher import shared_batcher
                        fetched = shared_batcher().fetch(state)
                        records = kernel.map_batch_drain(fetched, conf,
                                                         task_ctx)
                    else:
                        records = kernel.map_batch(batch, conf, task_ctx)
                    for key, value in records:
                        output.collect(key, value)
                    _mark_dispatched(name, batch)
        except Exception as e:  # noqa: BLE001 — classify at the site
            from tpumr.mapred.task import (classify_accelerator_exception,
                                           tag_failure)
            raise tag_failure(e, classify_accelerator_exception(
                e, compile_cold=temperature == "cold"))
        reporter.set_status(
            f"kernel {name} on {device}: "
            f"{getattr(batch, 'num_records', 0)} records in "
            f"{time.monotonic() - t0:.3f}s")


def stage_batch(conf, reader, task_ctx, device=None) -> tuple[Any, bool, int]:
    """Batch-native input formats hand over the split whole; otherwise
    drain the record reader into a RecordBatch (keys discarded — kernel
    inputs are values, matching the pipes data path where keys were
    offsets). With a ``device``, dense splits go through the HBM split
    cache: a cache hit skips storage I/O and the host→device transfer
    entirely; ``device=None`` stages on host (the CPU batch runner).
    Returns (batch, counted_by_reader, bytes_actually_staged)."""
    if device is not None:
        from tpumr.parallel.jaxruntime import configure_persistent_cache
        configure_persistent_cache(conf)
    in_fmt = new_instance(conf.get_input_format(), conf)
    split = None
    if task_ctx is not None and getattr(task_ctx, "split", None):
        split = InputSplit.from_dict(task_ctx.split)
    if split is not None and getattr(in_fmt, "read_batch", None) is not None:
        use_cache = conf.get_boolean("tpumr.tpu.split.cache", True)
        cache_mb = conf.get_int("tpumr.tpu.split.cache.mb", 2048)
        if device is not None and use_cache and isinstance(split, DenseSplit):
            import jax

            from tpumr.fs.filesystem import FileSystem
            cache = split_cache(device, cache_mb * 1024 * 1024)
            # file freshness (length, mtime) is part of the key so a
            # rewritten input never serves stale resident data
            st = FileSystem.get(split.path, conf).get_status(split.path)
            key = (split.path, split.row_start, split.num_rows,
                   split.dtype, split.data_offset, st.length, st.mtime)
            entry = cache.get(key)
            if entry is not None:
                staged, ids, meta = entry
                return DenseBatch(staged, ids, dict(meta)), False, 0
            # output chain: a predecessor job may have left this FILE's
            # image resident (device_output.publish) — slice the split's
            # rows on device, skipping the read AND the upload
            from tpumr.mapred import device_output
            whole = device_output.lookup(
                conf, device, FileSystem.get(split.path, conf),
                split.path, st.length, st.mtime)
            if (whole is not None and getattr(whole, "ndim", 0) == 2
                    and whole.shape[0] >= split.row_start + split.num_rows
                    and whole.shape[1] == split.cols
                    and str(whole.dtype) == str(np.dtype(split.dtype))):
                staged = whole[split.row_start:
                               split.row_start + split.num_rows]
                ids = np.arange(split.row_start,
                                split.row_start + split.num_rows,
                                dtype=np.int64)
                cache.put(key, (staged, ids, {}), int(staged.nbytes))
                return DenseBatch(staged, ids, {}), False, 0
            batch = in_fmt.read_batch(split, conf)
            staged = jax.device_put(batch.values, device)
            progress.tick(int(batch.values.nbytes), "stage")
            cache.put(key, (staged, batch.ids, dict(batch.meta)),
                      int(batch.values.nbytes))
            return DenseBatch(staged, batch.ids, batch.meta), False, \
                int(batch.values.nbytes)
        batch = in_fmt.read_batch(split, conf)
        return batch, False, int(getattr(batch, "nbytes", 0))
    values = []
    for _k, v in reader:
        if isinstance(v, (bytes, bytearray)):
            values.append(bytes(v))
        elif isinstance(v, str):
            values.append(v.encode("utf-8"))
        else:
            values.append(serialize(v))
    batch = RecordBatch.from_values(values)
    return batch, True, int(batch.nbytes)


def _select_device(dev_id: int):
    """The one device-binding rule (≈ GPUDeviceId → cudaSetDevice), shared
    by the per-task runner and the windowed prelaunch."""
    import jax
    devices = jax.local_devices()
    return devices[dev_id % len(devices)] if dev_id >= 0 else devices[0]


def _device_rows_of(kernel, state, conf):
    """The kernel's device output rows for chaining, or None — gated on
    the job's output format actually claiming them (DenseNpyOutputFormat)
    so other jobs can never strand HBM in the pending table."""
    if state is None:
        return None
    hook = getattr(kernel, "device_output_rows", None)
    if hook is None:
        return None
    try:
        fmt = conf.get_output_format()
    except Exception:  # noqa: BLE001 — unset/bogus output format
        return None
    if not getattr(fmt, "claims_device_rows", False):
        return None
    return hook(state)


def _offer_device_rows(kernel, state, conf) -> None:
    rows = _device_rows_of(kernel, state, conf)
    if rows is not None:
        from tpumr.mapred import device_output
        device_output.offer(str(conf.get("tpumr.task.attempt.id", "")),
                            rows)


class DevicePrefetch:
    """Fetched kernel output for one map task of a pipelined window.
    ``device_rows`` carries the still-resident output array when the job
    chains through DenseNpyOutputFormat (offered at drain time)."""

    __slots__ = ("fetched", "num_records", "staged_bytes", "device_rows")

    def __init__(self, fetched: Any, num_records: int,
                 staged_bytes: int, device_rows: Any = None) -> None:
        self.fetched = fetched
        self.num_records = num_records
        self.staged_bytes = staged_bytes
        self.device_rows = device_rows


def prelaunch_device_maps(conf, tasks: "list[Any]") -> "list[DevicePrefetch] | None":
    """Stage + dispatch a window of map tasks' kernels, then fetch EVERY
    task's device output in ONE ``jax.device_get`` — one tunnel roundtrip
    for the whole window instead of one per output array per task.

    Why this exists: on a tunneled/remote TPU runtime each host transfer
    of a computed array costs a full network roundtrip (~tens of ms) while
    dispatch is asynchronous and ~free, so per-task fetches dominate warm
    job wall-clock once compute is fast. Dispatching a window of tasks
    back-to-back also overlaps their device compute. This deepens the
    north-star design (whole-split HBM staging replacing the reference's
    per-record socket loop, PipesGPUMapRunner.java:97-107) by one more
    level: per-JOB, not per-task, host synchronization.

    Returns one :class:`DevicePrefetch` per task — possibly for a PREFIX
    of ``tasks`` only: the whole window is device-resident until the
    fetch, so staging is byte-bounded (``tpumr.tpu.pipeline.window.mb``)
    and the window closes early once the budget is spent (always taking
    at least one task, so the job progresses). Returns None when the job
    is not eligible (no kernel, kernel without the launch/drain protocol,
    a custom TPU runner, or an input format that cannot hand over whole
    splits) — callers fall back to the per-task path.
    """
    import jax
    from tpumr.ops import get_kernel
    from tpumr.parallel.jaxruntime import configure_persistent_cache
    configure_persistent_cache(conf)

    name = conf.get_map_kernel()
    if not name:
        return None
    kernel = get_kernel(name)
    if not type(kernel).supports_launch():
        return None
    # a custom TPU runner (or a subclass overriding run) would ignore the
    # prefetch and redo the work — require the stock run method
    if conf.get_tpu_map_runner_class().run is not TpuMapRunner.run:
        return None
    in_fmt = new_instance(conf.get_input_format(), conf)
    if getattr(in_fmt, "read_batch", None) is None:
        return None
    if any(not getattr(t, "split", None) for t in tasks):
        return None
    # one window = one device: mirror the per-task binding (tpu_device_id)
    dev_ids = {getattr(t, "tpu_device_id", -1) for t in tasks}
    if len(dev_ids) != 1:
        return None
    device = _select_device(dev_ids.pop())

    budget = conf.get_int("tpumr.tpu.pipeline.window.mb", 2048) * 1024 * 1024
    states: list[Any] = []
    meta: list[tuple[int, int]] = []
    resident = 0
    with jax.default_device(device):
        for task in tasks:
            batch, _counted, staged_bytes = stage_batch(
                conf, None, task, device)
            state = kernel.map_batch_launch(batch, conf, task)
            if state is None:
                return None
            states.append(state)
            meta.append((int(getattr(batch, "num_records", 0)),
                         int(staged_bytes),
                         _device_rows_of(kernel, state, conf)))
            # every staged input stays device-resident until the window
            # fetch (cache hits were already resident — they don't count)
            resident += int(staged_bytes)
            if resident >= budget and len(states) < len(tasks):
                break  # close the window early; caller resumes after us
        fetched = jax.device_get(states)  # ONE roundtrip for the window
        progress.tick(sum(m[1] for m in meta), "window-drain")
    return [DevicePrefetch(f, n, b, rows)
            for f, (n, b, rows) in zip(fetched, meta)]


class CpuBatchMapRunner(MapRunnable):
    """CPU-slot whole-batch runner — the vectorized host twin of
    :class:`TpuMapRunner`. The reference's hybrid premise is that CPU slots
    carry real work (3 CPU + 1 GPU slots per node,
    JobQueueTaskScheduler.java:127-178): per-record Python would make the
    CPU backend artificially slow and inflate the measured acceleration
    factor, so kernel jobs whose kernel provides ``map_batch_cpu`` (numpy)
    process the whole staged split per task here, exactly like the device
    path minus the device."""

    def configure(self, conf) -> None:
        self.conf = conf

    def run(self, reader, output, reporter, task_ctx=None) -> None:
        from tpumr.ops import get_kernel

        conf = self.conf
        kernel = get_kernel(conf.get_map_kernel())
        assert kernel.map_batch_cpu is not None  # selection checked upstream
        batch, counted_by_reader, _ = stage_batch(conf, reader, task_ctx)
        if not counted_by_reader:
            reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                  TaskCounter.MAP_INPUT_RECORDS,
                                  getattr(batch, "num_records", 0))
        reporter.incr_counter(BackendCounter.GROUP,
                              BackendCounter.CPU_BATCH_MAP_TASKS)
        t0 = time.monotonic()
        with runner_metrics().histogram("tpu_cpu_batch_seconds").time():
            for key, value in kernel.map_batch_cpu(batch, conf, task_ctx):
                output.collect(key, value)
        reporter.set_status(
            f"cpu-batch kernel {kernel.name}: "
            f"{getattr(batch, 'num_records', 0)} records in "
            f"{time.monotonic() - t0:.3f}s")
