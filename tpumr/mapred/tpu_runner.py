"""TPU map runner — placeholder until the device path lands (stage 3).

Replaces the reference's PipesGPUMapRunner (mapred/pipes/
PipesGPUMapRunner.java:40-118): instead of forking a CUDA binary and
streaming records over a socket, the runner stages the whole split into HBM
and executes the mapper as a JAX/Pallas kernel.
"""

from __future__ import annotations

from tpumr.mapred.api import MapRunnable


class TpuMapRunner(MapRunnable):
    def configure(self, conf) -> None:
        self.conf = conf

    def run(self, reader, output, reporter, task_ctx=None) -> None:
        raise NotImplementedError(
            "TPU map runner arrives with tpumr.ops (stage 3); "
            "set tpumr.map.kernel and use a registered kernel mapper")
