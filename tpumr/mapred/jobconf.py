"""JobConf — the per-job configuration facade.

≈ ``org.apache.hadoop.mapred.JobConf`` (reference: src/mapred/org/apache/
hadoop/mapred/JobConf.java, ~2100 LoC): a Configuration plus typed accessors
for the MapReduce job contract. Key names keep the reference's spelling where
a direct equivalent exists (so its GPU keys map 1:1 to TPU keys):

- ``mapred.tasktracker.map.cpu.tasks.maximum``  (TaskTracker.java:1427)
- ``mapred.tasktracker.map.tpu.tasks.maximum``  (≈ ...map.gpu.tasks.maximum, :1429)
- ``mapred.jobtracker.map.optionalscheduling``  (JobQueueTaskScheduler.java:78)
- ``tpumr.map.kernel``                          (≈ hadoop.pipes.gpu.executable,
  Submitter.java:110 — here it names a registered Pallas kernel mapper
  instead of a CUDA binary)
- ``mapred.map.runner.tpu.class``               (≈ mapred.map.runnner.gpu.class,
  JobConf.java:978 — the reference's getter/setter key typo is documented and
  intentionally NOT reproduced)
"""

from __future__ import annotations

from typing import Any

from tpumr.core.configuration import Configuration
from tpumr.core import confkeys

#: keys whose job-layer baseline IS the registry default — seeded from
#: tpumr/core/confkeys.py so the generated reference (docs/CONFIG.md)
#: and the runtime defaults can never diverge (tpumr lint guards
#: call-site literals; this guards the resource layer). Per-key docs
#: live in the registry. Dual slot pools ≈ reference
#: conf/mapred-site.xml:23-33 (3 CPU + 1 GPU map slots).
_REGISTRY_SEEDED = (
    "mapred.reduce.tasks",
    "mapred.map.max.attempts",
    "mapred.reduce.max.attempts",
    "mapred.task.timeout",
    "io.sort.mb",
    "io.sort.spill.percent",
    "io.sort.factor",
    "mapred.compress.map.output",
    "mapred.map.output.compression.codec",
    "mapred.min.split.size",
    "mapred.max.split.size",
    "mapred.tasktracker.map.cpu.tasks.maximum",
    "mapred.tasktracker.map.tpu.tasks.maximum",
    "mapred.tasktracker.reduce.tasks.maximum",
    "mapred.jobtracker.map.optionalscheduling",
    "mapred.reduce.slowstart.completed.maps",
    "mapred.speculative.execution",
    "mapred.job.shuffle.input.buffer.percent",
    "mapred.job.shuffle.merge.percent",
    "tpumr.shuffle.merge.enabled",
    "tpumr.shuffle.parallel.copies",
    "tpumr.tpu.attempt.retries",
    "tpumr.tpu.job.quarantine.tips",
    "tpumr.tpu.device.quarantine.failures",
    "tpumr.tpu.device.probe.interval.ms",
    "tpumr.tpu.device.probe.max.interval.ms",
)

DEFAULTS: dict[str, Any] = {
    **{k: confkeys.default_of(k) for k in _REGISTRY_SEEDED},
    # job-layer-only parameters consumed through this layer (no
    # conf-getter read sites, hence no registry entry)
    "io.file.buffer.size": 65536,
    "fs.local.block.size": 32 * 1024 * 1024,
}


class JobConf(Configuration):
    def __init__(self, other: Configuration | None = None) -> None:
        super().__init__(other=other, load_defaults=other is None)
        if other is None or not isinstance(other, JobConf):
            # DEFAULTS as lowest layer
            self._resources.insert(0, dict(DEFAULTS))

    # ------------------------------------------------------------ identity

    @property
    def job_name(self) -> str:
        return self.get("mapred.job.name", "")

    def set_job_name(self, name: str) -> None:
        self.set("mapred.job.name", name)

    # ------------------------------------------------------------ io paths

    def set_input_paths(self, *paths: str) -> None:
        self.set("mapred.input.dir", ",".join(paths))

    def get_input_paths(self) -> list[str]:
        return self.get_strings("mapred.input.dir")

    def add_input_path(self, path: str) -> None:
        cur = self.get_strings("mapred.input.dir")
        self.set("mapred.input.dir", ",".join(cur + [path]))

    def set_output_path(self, path: str) -> None:
        self.set("mapred.output.dir", path)

    def get_output_path(self) -> str | None:
        return self.get("mapred.output.dir")

    # ------------------------------------------------------------ task counts

    @property
    def num_reduce_tasks(self) -> int:
        return confkeys.get_int(self, "mapred.reduce.tasks")

    def set_num_reduce_tasks(self, n: int) -> None:
        self.set("mapred.reduce.tasks", n)

    @property
    def num_map_tasks_hint(self) -> int:
        return confkeys.get_int(self, "mapred.map.tasks")

    def set_num_map_tasks_hint(self, n: int) -> None:
        self.set("mapred.map.tasks", n)

    # ------------------------------------------------------------ classes

    def set_mapper_class(self, cls: type) -> None:
        self.set_class("mapred.mapper.class", cls)

    def get_mapper_class(self) -> type | None:
        return self.get_class("mapred.mapper.class")

    def set_reducer_class(self, cls: type) -> None:
        self.set_class("mapred.reducer.class", cls)

    def get_reducer_class(self) -> type | None:
        return self.get_class("mapred.reducer.class")

    def set_combiner_class(self, cls: type) -> None:
        self.set_class("mapred.combiner.class", cls)

    def get_combiner_class(self) -> type | None:
        return self.get_class("mapred.combiner.class")

    def set_partitioner_class(self, cls: type) -> None:
        self.set_class("mapred.partitioner.class", cls)

    def get_partitioner_class(self) -> type:
        from tpumr.mapred.api import HashPartitioner
        return self.get_class("mapred.partitioner.class", HashPartitioner)

    def set_input_format(self, cls: type) -> None:
        self.set_class("mapred.input.format.class", cls)

    def get_input_format(self) -> type:
        from tpumr.mapred.input_formats import TextInputFormat
        return self.get_class("mapred.input.format.class", TextInputFormat)

    def set_output_format(self, cls: type) -> None:
        self.set_class("mapred.output.format.class", cls)

    def get_output_format(self) -> type:
        from tpumr.mapred.output_formats import TextOutputFormat
        return self.get_class("mapred.output.format.class", TextOutputFormat)

    def set_output_key_comparator_class(self, cls: type) -> None:
        self.set_class("mapred.output.key.comparator.class", cls)

    def get_output_key_comparator(self) -> Any:
        from tpumr.mapred.api import DeserializingComparator
        from tpumr.utils.reflection import new_instance
        cls = self.get_class("mapred.output.key.comparator.class",
                             DeserializingComparator)
        # configured comparators (lib.KeyFieldBasedComparator reads its
        # -k options from conf) get the conf; plain ones ignore it
        return new_instance(cls, self)

    def set_output_value_grouping_comparator(self, cls: type) -> None:
        """≈ JobConf.setOutputValueGroupingComparator — the secondary-sort
        seam: reduce groups run under this comparator while the merge order
        stays the output-key comparator's."""
        self.set_class("mapred.output.value.groupfn.class", cls)

    def get_output_value_grouping_comparator(self) -> Any:
        from tpumr.utils.reflection import new_instance
        cls = self.get_class("mapred.output.value.groupfn.class")
        # conf-configured comparators (lib.KeyFieldBasedComparator) need
        # their options here too, same as get_output_key_comparator
        return new_instance(cls, self) if cls is not None else None

    def set_map_runner_class(self, cls: type) -> None:
        """≈ JobConf.setMapRunnerClass (CPU path)."""
        self.set_class("mapred.map.runner.class", cls)

    def get_map_runner_class(self) -> type:
        from tpumr.mapred.api import MapRunner
        return self.get_class("mapred.map.runner.class", MapRunner)

    def set_tpu_map_runner_class(self, cls: type) -> None:
        """≈ JobConf.setGPUMapRunnerClass (JobConf.java:977-1001; the
        reference's mapred.map.runnner.gpu.class getter typo is fixed here,
        divergence documented)."""
        self.set_class("mapred.map.runner.tpu.class", cls)

    def get_tpu_map_runner_class(self) -> type:
        from tpumr.mapred.tpu_runner import TpuMapRunner
        return self.get_class("mapred.map.runner.tpu.class", TpuMapRunner)

    # ------------------------------------------------------------ TPU kernel

    def set_map_kernel(self, name: str) -> None:
        """Name a registered device kernel mapper (tpumr.ops registry) —
        the TPU analog of hadoop.pipes.gpu.executable: without it a job is
        CPU-only in the hybrid scheduler (JobQueueTaskScheduler.java:342-347
        semantics preserved)."""
        self.set("tpumr.map.kernel", name)

    def get_map_kernel(self) -> str | None:
        return self.get("tpumr.map.kernel")

    def set_device_shuffle(self, key_bytes: int, value_bytes: int) -> None:
        """Opt this job into the device-shuffled reduce (ICI all_to_all +
        per-device sort — tpumr.mapred.device_shuffle): map outputs must be
        fixed-width ``bytes`` keys/values of exactly these lengths."""
        self.set("tpumr.shuffle.device", True)
        self.set("tpumr.shuffle.device.key.bytes", key_bytes)
        self.set("tpumr.shuffle.device.value.bytes", value_bytes)

    # ------------------------------------------------------------ slot pools

    @property
    def max_cpu_map_slots(self) -> int:
        return confkeys.get_int(
            self, "mapred.tasktracker.map.cpu.tasks.maximum")

    @property
    def max_tpu_map_slots(self) -> int:
        return confkeys.get_int(
            self, "mapred.tasktracker.map.tpu.tasks.maximum")

    @property
    def max_reduce_slots(self) -> int:
        return confkeys.get_int(
            self, "mapred.tasktracker.reduce.tasks.maximum")

    @property
    def optional_scheduling(self) -> bool:
        return confkeys.get_boolean(
            self, "mapred.jobtracker.map.optionalscheduling")

    # ------------------------------------------------------------ sort/spill

    @property
    def sort_mb(self) -> int:
        return confkeys.get_int(self, "io.sort.mb")

    @property
    def spill_percent(self) -> float:
        return confkeys.get_float(self, "io.sort.spill.percent")

    @property
    def sort_factor(self) -> int:
        return confkeys.get_int(self, "io.sort.factor")

    @property
    def compress_map_output(self) -> str:
        if confkeys.get_boolean(self, "mapred.compress.map.output"):
            return self.get("mapred.map.output.compression.codec", "zlib")
        return "none"
