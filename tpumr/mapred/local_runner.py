"""LocalJobRunner — in-process job execution, no daemons.

≈ ``org.apache.hadoop.mapred.LocalJobRunner`` (reference: src/mapred/org/
apache/hadoop/mapred/LocalJobRunner.java:51): the same submission surface as
the distributed runtime (splits → map attempts → shuffle → reduce attempts →
commit) executed in one process; the debugging/API-testing tier of the
reference's test strategy (SURVEY.md §4.3). Map tasks run on a thread pool
(``mapred.local.map.tasks.maximum``); with a registered device kernel
(JobConf.set_map_kernel) maps run through the TPU runner when
``tpumr.local.run.on.tpu`` is set — the single-process analog of hybrid
placement.
"""

from __future__ import annotations

import shutil
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from tpumr.core.counters import Counters, JobCounter, TaskCounter
from tpumr.mapred.api import Reporter
from tpumr.mapred.ids import JobID, TaskAttemptID, TaskID
from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.map_task import run_map_task
from tpumr.mapred.output_formats import FileOutputCommitter
from tpumr.mapred.reduce_task import local_fetch_factory, run_reduce_task
from tpumr.mapred.task import Task
from tpumr.utils.reflection import new_instance


@dataclass
class JobResult:
    job_id: JobID
    successful: bool
    counters: Counters = field(default_factory=Counters)
    num_maps: int = 0
    num_reduces: int = 0
    wall_time: float = 0.0
    error: str = ""


class LocalJobRunner:
    _seq = 0
    _seq_lock = threading.Lock()

    def __init__(self, conf: JobConf | None = None) -> None:
        self.conf = conf or JobConf()

    def submit_job(self, job_conf: JobConf) -> JobResult:
        with LocalJobRunner._seq_lock:
            LocalJobRunner._seq += 1
            job_id = JobID("local", LocalJobRunner._seq)
        t0 = time.monotonic()
        work_root = tempfile.mkdtemp(prefix=f"tpumr-{job_id}-")
        counters = Counters()
        try:
            result = self._run(job_id, job_conf, work_root, counters)
            result.wall_time = time.monotonic() - t0
            return result
        finally:
            shutil.rmtree(work_root, ignore_errors=True)

    def _run(self, job_id: JobID, conf: JobConf, work_root: str,
             counters: Counters) -> JobResult:
        from tpumr.mapred.device_shuffle import (is_device_shuffle,
                                                prepare_device_shuffle_job)
        prepare_device_shuffle_job(conf)  # collapses reduces to 1 gang task
        in_fmt = new_instance(conf.get_input_format(), conf)
        out_fmt = new_instance(conf.get_output_format(), conf)
        out_fmt.check_output_specs(conf)
        splits = in_fmt.get_splits(conf, conf.num_map_tasks_hint)
        num_reduces = conf.num_reduce_tasks
        committer = FileOutputCommitter(conf)
        committer.setup_job()

        run_on_tpu = (conf.get_boolean("tpumr.local.run.on.tpu", False)
                      and (conf.get_map_kernel() is not None
                           or bool(conf.get("tpumr.pipes.tpu.executable"))))

        # ---- map phase
        map_outputs: list[tuple[str, dict] | None] = [None] * len(splits)
        tasks = [
            Task(TaskAttemptID(TaskID(job_id, True, i), 0), partition=i,
                 num_reduces=num_reduces, split=splits[i].to_dict(),
                 run_on_tpu=run_on_tpu,
                 tpu_device_id=0 if run_on_tpu else -1)
            for i in range(len(splits))
        ]

        def one_map(i: int) -> None:
            task = tasks[i]
            reporter = Reporter()
            local_dir = f"{work_root}/map_{i:06d}"
            out = run_map_task(conf, task, local_dir, reporter)
            task.__dict__.pop("_device_prefetch", None)  # free window memory
            if num_reduces == 0 or \
                    committer.needs_commit(str(task.attempt_id)):
                # the OR arm: map-side named outputs (lib.MultipleOutputs)
                # in jobs with reducers
                committer.commit_task(str(task.attempt_id))
            map_outputs[i] = out
            counters.merge(reporter.counters)
            counters.incr(JobCounter.GROUP, JobCounter.LAUNCHED_MAP_TASKS)

        pool_size = conf.get_int("mapred.local.map.tasks.maximum", 1)
        if pool_size > 1:
            with ThreadPoolExecutor(max_workers=pool_size) as pool:
                list(pool.map(one_map, range(len(splits))))
        else:
            # TPU kernel jobs run map windows through the two-phase device
            # pipeline: dispatch a whole window of kernels, fetch every
            # task's output in ONE device_get (tpu_runner.prelaunch_device_
            # maps), then drain each task through the normal collect/spill
            # path — tunnel roundtrips per job drop from O(tasks) to
            # O(tasks / window)
            window = (conf.get_int("tpumr.tpu.pipeline.window", 32)
                      if run_on_tpu else 0)
            lo = 0
            while lo < len(splits):
                hi = min(lo + window, len(splits)) if window > 0 else len(splits)
                if window > 0:
                    from tpumr.mapred.tpu_runner import prelaunch_device_maps
                    pre = prelaunch_device_maps(conf, tasks[lo:hi])
                    if pre is None:
                        window, hi = 0, len(splits)  # ineligible: plain path
                    else:
                        hi = lo + len(pre)  # byte budget may shorten a window
                        for t, p in zip(tasks[lo:hi], pre):
                            t._device_prefetch = p
                for i in range(lo, hi):
                    one_map(i)
                lo = hi

        # ---- reduce phase
        if num_reduces > 0 and is_device_shuffle(conf):
            # ONE gang task owns the local mesh: exchange + sort on device
            from tpumr.mapred.device_shuffle import (local_dense_fetch,
                                                    run_device_reduce)
            attempt = TaskAttemptID(TaskID(job_id, False, 0), 0)
            task = Task(attempt, partition=0, num_reduces=1,
                        num_maps=len(splits))
            reporter = Reporter()
            run_device_reduce(conf, task, local_dense_fetch(map_outputs),
                              reporter)
            committer.commit_task(str(attempt))
            counters.merge(reporter.counters)
            counters.incr(JobCounter.GROUP, JobCounter.LAUNCHED_REDUCE_TASKS)
        elif num_reduces > 0:
            fetch = local_fetch_factory([mo for mo in map_outputs])  # type: ignore[misc]
            for r in range(num_reduces):
                attempt = TaskAttemptID(TaskID(job_id, False, r), 0)
                task = Task(attempt, partition=r, num_reduces=num_reduces,
                            num_maps=len(splits))
                reporter = Reporter()
                run_reduce_task(conf, task, fetch, reporter)
                committer.commit_task(str(attempt))
                counters.merge(reporter.counters)
                counters.incr(JobCounter.GROUP, JobCounter.LAUNCHED_REDUCE_TASKS)

        committer.commit_job()
        return JobResult(job_id, True, counters, len(splits), num_reduces)


def run_job(conf: JobConf) -> JobResult:
    """≈ JobClient.runJob: submit and wait (local by default; the distributed
    client takes over when mapred.job.tracker is set — stage 5)."""
    return LocalJobRunner().submit_job(conf)
