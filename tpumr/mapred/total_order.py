"""Global-sort support: input sampling + range partitioning.

≈ the reference's ``mapred/lib/TotalOrderPartitioner.java`` +
``mapred/lib/InputSampler.java`` (used by TeraSort — the reference's
terasort ships its own sampler in ``examples/terasort/TeraInputFormat``).
The sampler draws keys from the job's input splits, picks R-1 evenly
spaced cut points, and writes them to a partition file; the partitioner
bisects each map-output key against the cut points so reduce r receives
exactly the keys in (cut[r-1], cut[r]] — per-reduce sorted output is then
globally sorted by part index.
"""

from __future__ import annotations

import bisect
from typing import Any

from tpumr.fs import get_filesystem
from tpumr.io.writable import deserialize, serialize
from tpumr.mapred.api import Partitioner
from tpumr.utils.reflection import new_instance

PARTITION_PATH_KEY = "total.order.partitioner.path"


def sample_input(conf: Any, num_samples: int = 1000,
                 max_splits: int = 10) -> list:
    """Draw up to ``num_samples`` keys from the job's input (SplitSampler
    semantics: evenly across the first ``max_splits`` splits)."""
    input_format = new_instance(conf.get_input_format(), conf)
    splits = input_format.get_splits(conf, conf.num_map_tasks_hint)
    splits = splits[:max_splits]
    if not splits:
        return []
    per_split = max(1, num_samples // len(splits))
    samples: list = []
    for split in splits:
        reader = input_format.get_record_reader(split, conf)
        for i, (key, _value) in enumerate(reader):
            if i >= per_split:
                break
            samples.append(key)
    return samples


def write_partition_file(conf: Any, path: str, samples: list,
                         num_reduces: int) -> None:
    """Pick R-1 cut points from sorted samples and persist them; also sets
    the conf key the partitioner reads (≈ TotalOrderPartitioner.setPartitionFile)."""
    cuts: list = []
    if num_reduces > 1 and samples:
        ordered = sorted(samples)
        step = len(ordered) / num_reduces
        last = None
        for r in range(1, num_reduces):
            cand = ordered[min(len(ordered) - 1, int(round(r * step)))]
            if last is None or cand > last:
                cuts.append(cand)
                last = cand
    fs = get_filesystem(path, conf)
    fs.write_bytes(path, serialize(cuts))
    conf.set(PARTITION_PATH_KEY, path)


class TotalOrderPartitioner(Partitioner):
    """Range partitioner over the persisted cut points. Keys equal to a cut
    point go right (bisect_left), matching the reference's binary-search
    convention for the last key <= cut."""

    def __init__(self) -> None:
        self._cuts: list | None = None

    def configure(self, conf: Any) -> None:
        path = conf.get(PARTITION_PATH_KEY)
        if not path:
            raise ValueError(f"{PARTITION_PATH_KEY} not set — call "
                             "write_partition_file before submitting")
        fs = get_filesystem(path, conf)
        self._cuts = deserialize(fs.read_bytes(path))

    def get_partition(self, key: Any, value: Any, num_partitions: int) -> int:
        assert self._cuts is not None, "partitioner not configured"
        return min(bisect.bisect_left(self._cuts, key), num_partitions - 1)
