"""Queue administration: per-queue submit/administer ACLs.

≈ ``org.apache.hadoop.mapred.QueueManager`` + ``conf/mapred-queue-acls.xml``
(reference: src/mapred/org/apache/hadoop/mapred/QueueManager.java — queue
set from ``mapred.queue.names``, ACL enforcement gated on
``mapred.acls.enabled``, per-queue keys
``mapred.queue.<name>.acl-submit-job`` / ``acl-administer-jobs``, checked
at submit and at job kill/modify). Reference ACL syntax kept:

- ``*``                      — everyone
- ``user1,user2 group1,...`` — space-separated user list then group list
- `` `` (blank)              — no one (owner/superuser still pass)

Identity is the simple-auth model the rest of the framework uses
(UserGroupInformation: asserted, not cryptographically proven — exactly
the reference's non-Kerberos default; see docs/OPERATIONS.md threat
model). The job OWNER and the cluster superuser
(``mapred.cluster.administrators`` users/groups) always administer.
"""

from __future__ import annotations

from typing import Any

from tpumr.security import UserGroupInformation

QUEUE_NAMES_KEY = "mapred.queue.names"
ACLS_ENABLED_KEY = "mapred.acls.enabled"
JOB_QUEUE_KEY = "mapred.job.queue.name"
ADMINS_KEY = "mapred.cluster.administrators"
DEFAULT_QUEUE = "default"


class AccessControlList:
    """One ACL entry, reference syntax (users SP groups | ``*``)."""

    def __init__(self, spec: str) -> None:
        raw = spec if spec is not None else ""
        self.spec = raw.strip()
        self.all = self.spec == "*"
        users: set[str] = set()
        groups: set[str] = set()
        if not self.all and self.spec:
            # Split on the FIRST space WITHOUT stripping first: the
            # reference's groups-only form is a leading blank
            # (" devs,ops" = no users, groups devs+ops —
            # AccessControlList.java split(" ", 2) semantics).
            parts = raw.split(" ", 1)
            users = {u.strip() for u in parts[0].split(",") if u.strip()}
            if len(parts) > 1:
                groups = {g.strip() for g in parts[1].split(",")
                          if g.strip()}
        self.users = users
        self.groups = groups

    def allows(self, ugi: UserGroupInformation) -> bool:
        if self.all:
            return True
        return (ugi.user in self.users
                or any(g in self.groups for g in ugi.groups))


#: optional separate hot-reloadable ACL file ≈ conf/mapred-queue-acls.xml
#: (the reference loads queue ACLs from their own resource so
#: ``mradmin -refreshQueues`` can re-read them without a restart)
ACLS_FILE_KEY = "mapred.queue.acls.file"


class QueueManager:
    def __init__(self, conf: Any) -> None:
        acls_file = conf.get(ACLS_FILE_KEY)
        if acls_file:
            # overlay the file as the TOPMOST resource layer: its keys
            # beat the daemon's startup resources (so a refresh takes
            # effect) but not explicit set()/-D overrides. Re-reading
            # happens by rebuilding the QueueManager (JobMaster.
            # refresh_queues ≈ AdminOperationsProtocol.refreshQueues).
            from tpumr.core.configuration import Configuration
            eff = Configuration(conf)
            eff.add_resource(str(acls_file))   # OSError -> caller; a
            # misconfigured ACL file must fail loudly, never silently
            # fall back to whatever the stale conf says
            conf = eff
        self.conf = conf
        explicit = conf.get(QUEUE_NAMES_KEY)
        names = str(explicit if explicit is not None
                    else (conf.get("tpumr.capacity.queues")
                          or DEFAULT_QUEUE))
        self.queue_names = [q.strip() for q in names.split(",") if q.strip()]
        self.acls_enabled = bool(conf.get_boolean(ACLS_ENABLED_KEY, False)) \
            if hasattr(conf, "get_boolean") else \
            str(conf.get(ACLS_ENABLED_KEY) or "").lower() == "true"
        # Queue EXISTENCE is enforced whenever the operator configured
        # mapred.queue.names explicitly, AND always once ACLs are on —
        # an ACL regime over phantom queues (each defaulting to open
        # "*") would silently bypass enforcement. Only with ACLs off
        # and no explicit names do the capacity scheduler's documented
        # phantom-bucket semantics (unconfigured queues scheduled last,
        # never rejected) stay intact; that narrower divergence from the
        # reference (QueueManager.java always validates) is documented.
        self.enforce_exists = explicit is not None or self.acls_enabled
        self._admins = AccessControlList(str(conf.get(ADMINS_KEY, "") or ""))

    # ------------------------------------------------------------ lookups

    def queues(self) -> "list[str]":
        return list(self.queue_names)

    def acl_spec(self, queue: str, op: str) -> str:
        """The raw ACL spec string for display (``tpumr queue -list`` ≈
        jobqueue_details.jsp's scheduling-info column)."""
        spec = self.conf.get(f"mapred.queue.{queue}.acl-{op}")
        return "*" if spec is None else str(spec)

    def operations_for(self, ugi: UserGroupInformation) -> "list[dict]":
        """Per-queue operations this user may perform — the payload of
        ``tpumr queue -showacls`` (≈ JobClient.getQueueAclsForCurrentUser
        → QueueManager.getQueueAcls)."""
        out = []
        for q in self.queue_names:
            ops = [op for op in ("submit-job", "administer-jobs")
                   if self.has_access(q, op, ugi)]
            out.append({"queue": q, "operations": ops})
        return out

    def _acl(self, queue: str, op: str) -> AccessControlList:
        spec = self.conf.get(f"mapred.queue.{queue}.acl-{op}")
        # unset = open, the reference's default (QueueManager.java: a
        # missing key behaves as "*")
        return AccessControlList("*" if spec is None else str(spec))

    # ------------------------------------------------------------- checks

    def is_admin(self, ugi: UserGroupInformation) -> bool:
        """Cluster administrator (``mapred.cluster.administrators``) —
        the identity tier above every queue ACL, and the gate for
        admin RPCs (refresh_queues ≈ AdminOperationsProtocol)."""
        return self._admins.allows(ugi)

    def has_access(self, queue: str, op: str,
                   ugi: UserGroupInformation) -> bool:
        """op ∈ {"submit-job", "administer-jobs"}."""
        if not self.acls_enabled:
            return True
        if self.is_admin(ugi):
            return True
        return self._acl(queue, op).allows(ugi)

    def check_queue_exists(self, queue: str) -> None:
        if self.enforce_exists and queue not in self.queue_names:
            raise PermissionError(
                f"queue {queue!r} is not defined; configured queues: "
                f"{', '.join(self.queue_names)} ({QUEUE_NAMES_KEY})")

    def check_submit(self, queue: str, ugi: UserGroupInformation) -> None:
        """Submit-time gate (≈ JobTracker.submitJob → QueueManager.
        hasAccess(SUBMIT_JOB)): the queue must exist AND allow this
        user. REJECTS — never deprioritizes — unauthorized submission."""
        self.check_queue_exists(queue)
        if not self.has_access(queue, "submit-job", ugi):
            raise PermissionError(
                f"user {ugi.user!r} cannot submit to queue {queue!r} "
                f"(mapred.queue.{queue}.acl-submit-job)")

    def check_administer(self, queue: str, ugi: UserGroupInformation,
                         owner: str) -> None:
        """Kill/modify gate (≈ QueueManager.hasAccess(ADMINISTER_JOBS),
        checked in JobTracker.killJob): the job owner always may; else
        queue administer ACL or cluster administrators."""
        if ugi.user == owner:
            return
        if not self.has_access(queue, "administer-jobs", ugi):
            raise PermissionError(
                f"user {ugi.user!r} cannot administer jobs in queue "
                f"{queue!r} (owner {owner!r}; "
                f"mapred.queue.{queue}.acl-administer-jobs)")
