"""Master brownout mode: ranked load-shedding under sustained SLO
pressure.

When the flight recorder's windowed SLOs stay breached for several
consecutive ticks, the master starts shedding DEFERRABLE work in ranked
steps — cheapest-to-lose first — and steps back up (most-expensive-shed
released first) once pressure clears:

  level 1  ``trace``        stop sampling new job traces (PR 2 span
                            plumbing costs allocation + journal I/O per
                            traced heartbeat; losing them loses
                            diagnosis detail, never correctness)
  level 2  ``cadence``      stretch the instructed heartbeat interval
                            toward ``tpumr.heartbeat.interval.max.ms``
                            via the adaptive-cadence channel (PR 8) —
                            trackers beat slower, the fold/assign path
                            breathes; task latency rises for everyone
  level 3  ``speculation``  pause speculative-attempt scans (twins are
                            pure opportunism under pressure) and
           ``history``      shed non-critical history I/O (TASK_STARTED
                            display events — the history server already
                            derives start times when they're absent)

The controller itself is a pure, clock-injectable state machine: the
flight recorder calls :meth:`JobMaster.brownout_tick` once per tick with
a boolean pressure signal, and everything the master sheds consults
:meth:`sheds` — one GIL-atomic attribute read, no locks on hot paths.
Transitions are remembered (bounded) so incident bundles can carry the
recent brownout trajectory, and the degradation is deliberately ranked
so interactive-class latency recovers at the expense of batch-class
conveniences, never the reverse.
"""

from __future__ import annotations

import time
from typing import Any, Callable

from tpumr.core import confkeys

#: shed steps gained per level, in rank order (index i = level i+1)
LEVELS: "tuple[frozenset, ...]" = (
    frozenset({"trace"}),
    frozenset({"cadence"}),
    frozenset({"speculation", "history"}),
)
MAX_LEVEL = len(LEVELS)


class BrownoutController:
    """Hysteretic level ladder driven by one pressure bit per tick.

    Step UP one level after ``engage_ticks`` consecutive pressure
    ticks; step DOWN one level after ``release_ticks`` consecutive
    clear ticks; ``dwell_s`` is the minimum time between transitions so
    a flapping signal can't saw the cadence. All mutation happens on
    the flight recorder's single tick thread; readers see a plain int.
    """

    def __init__(self, *, engage_ticks: int = 3, release_ticks: int = 3,
                 dwell_s: float = 3.0, cadence_factor: float = 3.0,
                 clock: "Callable[[], float]" = time.monotonic) -> None:
        self.level = 0
        self.engage_ticks = max(1, int(engage_ticks))
        self.release_ticks = max(1, int(release_ticks))
        self.dwell_s = max(0.0, float(dwell_s))
        self.cadence_factor = max(1.0, float(cadence_factor))
        self._clock = clock
        self._pressure_run = 0
        self._clear_run = 0
        self._last_change = -1e9
        self.step_ups = 0
        self.step_downs = 0
        #: history-event shed count (incremented by the master when a
        #: deferrable history append is dropped under level >= 3)
        self.events_shed = 0
        #: recent transitions, oldest first: (monotonic_ts, old, new)
        self.transitions: "list[tuple[float, int, int]]" = []

    @classmethod
    def from_conf(cls, conf: Any) -> "BrownoutController | None":
        """None unless ``tpumr.brownout.enabled`` — the controller is
        opt-in; a master without it never sheds anything."""
        if conf is None or not confkeys.get_boolean(
                conf, "tpumr.brownout.enabled"):
            return None
        return cls(
            engage_ticks=confkeys.get_int(
                conf, "tpumr.brownout.engage.ticks"),
            release_ticks=confkeys.get_int(
                conf, "tpumr.brownout.release.ticks"),
            dwell_s=confkeys.get_int(
                conf, "tpumr.brownout.dwell.ms") / 1000.0,
            cadence_factor=confkeys.get_float(
                conf, "tpumr.brownout.cadence.factor"))

    # ------------------------------------------------------------ ticks

    def on_tick(self, pressure: bool) -> int:
        """Fold one pressure observation; returns the (possibly new)
        level. Called from the flight recorder's tick thread only."""
        if pressure:
            self._pressure_run += 1
            self._clear_run = 0
        else:
            self._clear_run += 1
            self._pressure_run = 0
        now = self._clock()
        if now - self._last_change < self.dwell_s:
            return self.level
        if pressure and self._pressure_run >= self.engage_ticks \
                and self.level < MAX_LEVEL:
            self._change(self.level + 1, now)
            self._pressure_run = 0
        elif not pressure and self._clear_run >= self.release_ticks \
                and self.level > 0:
            self._change(self.level - 1, now)
            self._clear_run = 0
        return self.level

    def _change(self, new: int, now: float) -> None:
        old, self.level = self.level, new
        self._last_change = now
        if new > old:
            self.step_ups += 1
        else:
            self.step_downs += 1
        self.transitions.append((now, old, new))
        del self.transitions[:-64]

    # ------------------------------------------------------------ reads

    def sheds(self, step: str) -> bool:
        """Is ``step`` currently shed? Lock-free — one int read plus a
        frozenset probe; safe from every hot path."""
        level = self.level
        for i in range(min(level, MAX_LEVEL)):
            if step in LEVELS[i]:
                return True
        return False

    def stretch_interval(self, interval_s: float,
                         max_s: float) -> float:
        """The cadence shed: multiply the instructed heartbeat interval
        by the configured factor, capped at the adaptive-cadence max
        (``max_s``; never shrinks below the input either way)."""
        if not self.sheds("cadence"):
            return interval_s
        out = interval_s * self.cadence_factor
        if max_s > 0:
            out = min(out, max(max_s, interval_s))
        return out

    def snapshot(self) -> dict:
        """Bounded, JSON-safe state for incident-bundle annotation."""
        return {
            "level": self.level,
            "step_ups": self.step_ups,
            "step_downs": self.step_downs,
            "events_shed": self.events_shed,
            "sheds": sorted(s for lv in LEVELS[:self.level] for s in lv),
            "transitions": [
                {"ts_mono": round(ts, 3), "from": a, "to": b}
                for ts, a, b in self.transitions[-16:]],
        }
