"""Input splits ≈ ``org.apache.hadoop.mapred.InputSplit`` / ``FileSplit``
(reference: src/mapred/org/apache/hadoop/mapred/FileSplit.java): a byte range
of a file plus locality hints; computed by the InputFormat at submit time
(JobClient.writeSplits, mapred/JobClient.java:973-981) and shipped to map
tasks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class InputSplit:
    locations: list[str] = field(default_factory=list)

    @property
    def length(self) -> int:
        return 0

    def describe(self) -> str:
        return repr(self)

    # wire form for submission/staging

    def to_dict(self) -> dict[str, Any]:
        return {"type": f"{type(self).__module__}.{type(self).__qualname__}",
                **self.__dict__}

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "InputSplit":
        from tpumr.utils.reflection import resolve_class
        d = dict(d)
        cls = resolve_class(d.pop("type"))
        return cls(**d)


@dataclass
class FileSplit(InputSplit):
    path: str = ""
    start: int = 0
    split_length: int = 0

    @property
    def length(self) -> int:
        return self.split_length

    def describe(self) -> str:
        return f"{self.path}:{self.start}+{self.split_length}"


@dataclass
class DenseSplit(InputSplit):
    """A row range of a dense numeric dataset (K-Means points, matmul blocks):
    the unit the TPU runner stages into HBM in one transfer. ``path`` points
    at a .npy file; rows [row_start, row_start+num_rows). dtype/cols/
    data_offset are captured from the npy header at split time so readers can
    seek straight to the byte range without reparsing the file."""
    path: str = ""
    row_start: int = 0
    num_rows: int = 0
    row_bytes: int = 0
    dtype: str = "<f4"
    cols: int = 1
    data_offset: int = 0

    @property
    def length(self) -> int:
        return self.num_rows * self.row_bytes

    def describe(self) -> str:
        return f"{self.path}[rows {self.row_start}+{self.num_rows}]"
