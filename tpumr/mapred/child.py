"""Task child — the isolated per-attempt process main.

≈ ``org.apache.hadoop.mapred.Child`` (reference: src/mapred/org/apache/
hadoop/mapred/Child.java:69 main, :172 task fetch, :255 run): a separate
OS process per task attempt that talks to its tracker over an umbilical
RPC (≈ TaskUmbilicalProtocol, mapred/TaskUmbilicalProtocol.java:65) —
status/progress updates, kill polling, commit approval, and final
completion all flow through the tracker, never directly to the master.

Divergences from the reference, by design:

- the child is launched only for CPU map/reduce attempts when process
  isolation is enabled (``tpumr.task.isolation=process``): TPU tasks stay
  in the tracker process so kernels share one JAX runtime and the HBM
  split cache (tasktracker.py module docstring);
- task state is shipped in one self-contained task file (conf + task +
  umbilical address + job token) written into the attempt's sandbox dir,
  instead of being fetched over the umbilical after launch — one fewer
  startup round-trip, and it gives the setuid task-controller a single
  file whose ownership it can validate;
- there is no JVM-reuse pool (JvmManager.java:322-413): Python process
  startup is milliseconds, and idle-child reuse would keep dead task
  state alive across attempts.

The umbilical methods live on the tracker's existing RPC surface
(NodeRunner.umbilical_*). The child authenticates with its PER-JOB token
(≈ the reference's jobToken file + JobTokenSecretManager), never the
cluster secret: the RPC layer restricts token-scoped callers to the
umbilical/shuffle methods and each method pins the scope to its job.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Any

_PING_INTERVAL_S = 0.5
_STATUS_INTERVAL_S = 1.0


class _Umbilical:
    """Child side of the tracker umbilical: rate-limited kill polling and
    periodic status push (≈ Child.java's TaskReporter thread)."""

    def __init__(self, client: Any, aid: str) -> None:
        self.client = client
        self.aid = aid
        self._last_ping = 0.0
        self._killed = False

    def kill_requested(self) -> bool:
        # monotonic: the ping rate limit is interval arithmetic — a
        # clock step must not freeze (or flood) the kill poll
        now = time.monotonic()
        if self._killed:
            return True
        if now - self._last_ping >= _PING_INTERVAL_S:
            self._last_ping = now
            try:
                self._killed = bool(
                    self.client.call("umbilical_ping", self.aid))
            except Exception:  # noqa: BLE001 — tracker gone: die quietly
                self._killed = True
        return self._killed

    def push_status(self, reporter: Any, phase: str,
                    progress: float) -> None:
        try:
            self.client.call("umbilical_status", self.aid, {
                "phase": phase,
                "progress": progress,
                "counters": reporter.counters.to_dict(),
                "status": reporter.status,
                # liveness ticks for the tracker's reaper: the push
                # itself is a timer and must NOT count as progress — a
                # hung task keeps pushing identical payloads; only a
                # CHANGING tick count proves the task thread moves
                "ticks": reporter.ticks,
            })
        except Exception:  # noqa: BLE001
            pass


def run_child(task_file: str) -> int:
    """Execute the attempt described by ``task_file``; returns exit code."""
    from tpumr.io.writable import deserialize
    from tpumr.ipc.rpc import RpcClient
    from tpumr.mapred.api import Reporter, TaskKilledError
    from tpumr.mapred.jobconf import JobConf
    from tpumr.mapred.task import Task

    with open(task_file, "rb") as f:
        spec = deserialize(f.read())

    conf = JobConf()
    for k, v in spec["conf"].items():
        conf.set(k, v)
    task = Task.from_dict(spec["task"])
    job_id = spec["job_id"]
    aid = str(task.attempt_id)
    secret = spec.get("secret") or None
    scope = spec.get("scope") or None  # job-token identity (never the
    #                                    cluster secret — see process_runner)

    tracker = RpcClient(spec["tracker_host"], spec["tracker_port"],
                        secret=secret, scope=scope)
    umb = _Umbilical(tracker, aid)
    phase = ["MAP" if task.is_map else "SHUFFLE"]
    progress = [0.0]
    reporter = Reporter(abort_check=umb.kill_requested,
                        on_progress=lambda f: progress.__setitem__(0, f))

    stop = threading.Event()

    def status_loop() -> None:
        while not stop.wait(_STATUS_INTERVAL_S):
            umb.push_status(reporter, phase[0], progress[0])

    threading.Thread(target=status_loop, daemon=True,
                     name="umbilical-status").start()

    def can_commit() -> bool:
        return bool(tracker.call("umbilical_can_commit",
                                 str(task.task_id), aid))

    # distributed tracing: the task file's conf carries the trace flag +
    # dir and the Task carries the tracker's launch-span context — the
    # child's run span (and everything nested: spills, shuffle fetches)
    # joins the job trace across the process boundary
    from tpumr.core import tracing
    tracer = tracing.Tracer.from_conf(conf, "task") \
        if task.trace is not None else None
    run_span = None
    if tracer is not None:
        run_span = tracer.start_span(
            "task:run", task.trace["trace_id"], parent=task.trace,
            backend="cpu", attempt_id=aid, isolation="process",
            pid=os.getpid())

    trace_done_once = [False]

    def _trace_done(state: str) -> None:
        # idempotent: the success path finishes the span BEFORE the
        # umbilical_done RPC (so it can't be lost to a crash mid-call);
        # if that RPC then raises, the exception handler's call must not
        # write a second record with the same span_id
        if tracer is None or run_span is None or trace_done_once[0]:
            return
        trace_done_once[0] = True
        tracer.finish(run_span.set(state=state))
        tracer.flush()

    try:
        out_path, index = "", {}
        committed = True
        from tpumr.mapred.profiler import maybe_profile, profile_dir
        local_dir = os.path.dirname(os.path.abspath(task_file))
        prof_dir = profile_dir(conf, aid, local_dir)
        with tracing.activate(tracer, run_span):
            if task.is_map:
                from tpumr.mapred.map_task import run_map_task
                out_path, index = maybe_profile(
                    conf, task, prof_dir,
                    lambda: run_map_task(conf, task, local_dir, reporter))
                # direct-output maps AND map-side named outputs in jobs
                # with reducers; _commit no-ops with no files
                committed = _commit(conf, task, can_commit)
            else:
                from tpumr.mapred.reduce_task import run_reduce_task
                from tpumr.mapred.tasktracker import make_map_locator

                locate = make_map_locator(
                    lambda cursor: tracker.call("umbilical_events", job_id,
                                                cursor),
                    secret,
                    poll_s=conf.get_int("tpumr.shuffle.poll.ms",
                                        200) / 1000.0,
                    timeout_s=conf.get_int("tpumr.shuffle.timeout.ms",
                                           600_000) / 1000.0,
                    scope=scope)

                from tpumr.mapred.shuffle_copier import RemoteChunkSource
                conf.set("tpumr.task.local.dir",
                         os.path.join(local_dir, "shuffle"))
                fetch = RemoteChunkSource(conf, job_id, locate)

                def report_fetch_failure(map_index: int,
                                         map_attempt: str) -> None:
                    # best-effort: the copier's penalty/retry loop keeps
                    # the reduce alive even when the report can't be
                    # delivered
                    try:
                        tracker.call("umbilical_report_fetch_failure",
                                     aid, map_attempt)
                    except Exception:  # noqa: BLE001
                        pass

                fetch.on_fetch_failure = report_fetch_failure

                maybe_profile(conf, task, prof_dir,
                              lambda: run_reduce_task(conf, task, fetch,
                                                      reporter))
                phase[0] = "REDUCE"
                committed = _commit(conf, task, can_commit)
        stop.set()
        final = {
            "counters": reporter.counters.to_dict(),
            "progress": 1.0,
            "phase": phase[0],
            "state": "SUCCEEDED" if committed else "KILLED",
            "diagnostics": ("" if committed
                            else "commit denied: another attempt won"),
        }
        _trace_done(final["state"])
        tracker.call("umbilical_done", aid, final, job_id,
                     task.partition, out_path, index)
        return 0
    except TaskKilledError:
        stop.set()
        _trace_done("KILLED")
        _report_fail(tracker, aid, "KILLED",
                     "attempt killed while running (preempted or "
                     "superseded)")
        return 0
    except BaseException as e:  # noqa: BLE001 — task failure is data
        stop.set()
        diag = f"{type(e).__name__}: {e}\n" + traceback.format_exc(limit=8)
        if run_span is not None:
            run_span.set(error=diag.splitlines()[0])
        _trace_done("FAILED")
        # classification rides the umbilical so the master's demotion/
        # quarantine plane sees isolated attempts like in-process ones
        from tpumr.mapred.task import classify_exception
        _report_fail(tracker, aid, "FAILED", diag, classify_exception(e))
        return 1


def _commit(conf: Any, task: Any, can_commit: Any) -> bool:
    """Commit gate, child side (same contract as NodeRunner._commit): the
    tracker proxies the grant to the master; a losing attempt aborts its
    work dir and reports KILLED."""
    from tpumr.core import tracing
    from tpumr.mapred.output_formats import FileOutputCommitter
    committer = FileOutputCommitter(conf)
    aid = str(task.attempt_id)
    if not committer.needs_commit(aid):
        return True
    with tracing.span("task:commit", attempt_id=aid) as s:
        if can_commit():
            committer.commit_task(aid)
            return True
        if s is not None:
            s.set(denied=True)
        committer.abort_task(aid)
        return False


def _report_fail(tracker: Any, aid: str, state: str, diag: str,
                 failure_class: str = "") -> None:
    try:
        tracker.call("umbilical_fail", aid, state, diag, failure_class)
    except Exception:  # noqa: BLE001 — tracker reaps us by exit code
        pass


def main(argv: "list[str] | None" = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m tpumr.mapred.child <task-file>",
              file=sys.stderr)
        return 2
    return run_child(argv[0])


if __name__ == "__main__":
    sys.exit(main())
