"""Database input/output formats — the ``mapred.lib.db`` tier.

≈ ``src/mapred/org/apache/hadoop/mapred/lib/db/`` (``DBInputFormat``'s
COUNT + LIMIT/OFFSET splitting, DBInputFormat.java:114-115,339;
``DBOutputFormat``'s constructed INSERT, DBOutputFormat.java:109-158;
``DBConfiguration``'s connection keys): read a table or query as map
input, one LIMIT/OFFSET window per split, and write reduce output back
as INSERTs.

JDBC → DB-API 2.0: the connection is built from
``tpumr.db.module`` (importable DB-API module name, default
``sqlite3`` — in the standard library, so the tier works everywhere)
and ``tpumr.db.connect`` (the argument passed to ``module.connect``;
for sqlite3 the database path). Rows travel as plain tuples (the
DBWritable role is played by ordinary serialization — tuples are
already Writable here).

Caveats carried over from the reference, documented not hidden:
LIMIT/OFFSET windows are only a STABLE partition when the query is
deterministically ordered — ``tpumr.db.input.order.by`` is required for
multi-split reads unless ``tpumr.db.input.query`` already orders
(DBInputFormat had the same hazard and shipped it silently);
DBOutputFormat writes through the task's own connection at close — use
one reduce or idempotent inserts if re-execution matters (same caveat
as the reference's direct-write design).
"""

from __future__ import annotations

import importlib
from typing import Any, Iterator

from tpumr.mapred.split import InputSplit

MODULE_KEY = "tpumr.db.module"
CONNECT_KEY = "tpumr.db.connect"
INPUT_TABLE_KEY = "tpumr.db.input.table"
INPUT_FIELDS_KEY = "tpumr.db.input.fields"
INPUT_QUERY_KEY = "tpumr.db.input.query"
INPUT_COUNT_QUERY_KEY = "tpumr.db.input.count.query"
INPUT_ORDER_KEY = "tpumr.db.input.order.by"
OUTPUT_TABLE_KEY = "tpumr.db.output.table"
OUTPUT_FIELDS_KEY = "tpumr.db.output.fields"


def _db_module(conf: Any):
    return importlib.import_module(
        str(conf.get(MODULE_KEY, "sqlite3") or "sqlite3"))


def db_connect(conf: Any):
    module = _db_module(conf)
    connect = conf.get(CONNECT_KEY)
    if not connect:
        raise ValueError(f"{CONNECT_KEY} not set (the module.connect() "
                         f"argument — for sqlite3, the database path)")
    return module.connect(str(connect))


def db_placeholder(conf: Any) -> str:
    """The module's DB-API paramstyle as an INSERT placeholder — qmark
    drivers (sqlite3) take '?', format/pyformat drivers (psycopg2,
    MySQLdb) take '%s'; hardcoding either breaks the other family."""
    style = getattr(_db_module(conf), "paramstyle", "qmark")
    if style in ("format", "pyformat"):
        return "%s"
    if style in ("qmark", "numeric", "named"):
        return "?"          # numeric/named also accept qmark-free SQL
                            # rarely; qmark is the broadest safe default
    return "?"


def _ident(name: str) -> str:
    """Identifier hygiene for table/field names spliced into SQL (the
    reference spliced raw conf values; a conf is operator-trusted, but
    a typo'd quote should fail loudly, not truncate a statement)."""
    clean = name.strip()
    if not clean or not all(c.isalnum() or c in "_." for c in clean):
        raise ValueError(f"bad SQL identifier from conf: {name!r}")
    return clean


def _order_spec(spec: str) -> str:
    """ORDER BY grammar: comma-separated identifiers, each optionally
    followed by ASC/DESC — 'id DESC' and 'id, ts' are legitimate sort
    keys, not identifier typos."""
    parts = []
    for term in str(spec).split(","):
        bits = term.split()
        if not bits or len(bits) > 2:
            raise ValueError(f"bad ORDER BY term: {term!r}")
        col = _ident(bits[0])
        if len(bits) == 2:
            if bits[1].upper() not in ("ASC", "DESC"):
                raise ValueError(f"bad ORDER BY direction: {bits[1]!r}")
            col += " " + bits[1].upper()
        parts.append(col)
    return ", ".join(parts)


def _select_query(conf: Any) -> str:
    query = conf.get(INPUT_QUERY_KEY)
    if query:
        return str(query)
    table = conf.get(INPUT_TABLE_KEY)
    if not table:
        raise ValueError(f"set {INPUT_TABLE_KEY} or {INPUT_QUERY_KEY}")
    fields = conf.get(INPUT_FIELDS_KEY)
    cols = ", ".join(_ident(f) for f in str(fields).split(",")) \
        if fields else "*"
    sql = f"SELECT {cols} FROM {_ident(str(table))}"
    order = conf.get(INPUT_ORDER_KEY)
    if order:
        sql += f" ORDER BY {_order_spec(str(order))}"
    return sql


class DBSplit(InputSplit):
    """(offset, row_count) window of the ordered query ≈ DBInputFormat's
    DBInputSplit. Serializes through the generic InputSplit wire form
    (type + __dict__)."""

    def __init__(self, start: int = 0, row_count: int = 0,
                 locations: "list | None" = None) -> None:
        self.start = int(start)
        self.row_count = int(row_count)
        self.locations = list(locations or [])  # the db is everywhere

    @property
    def length(self) -> int:
        return self.row_count

    def describe(self) -> str:
        return f"rows {self.start}+{self.row_count}"


class _DBRecordReader:
    """Yields (row_index, row_tuple) ≈ (LongWritable, DBWritable)."""

    def __init__(self, conf: Any, split: DBSplit) -> None:
        self.conn = db_connect(conf)
        sql = (f"{_select_query(conf)} LIMIT {split.row_count} "
               f"OFFSET {split.start}")
        self.cursor = self.conn.cursor()
        self.cursor.execute(sql)
        self.base = split.start

    def __iter__(self) -> "Iterator[tuple[int, tuple]]":
        try:
            # try/finally, not drain-then-close: a mapper exception or
            # the runner's abort check abandons this generator mid-way,
            # and the connection must not wait for GC
            for i, row in enumerate(self.cursor):
                yield self.base + i, tuple(row)
        finally:
            self.close()

    def close(self) -> None:
        try:
            self.cursor.close()
            self.conn.close()
        except Exception:  # noqa: BLE001 — double-close etc.
            pass


class DBInputFormat:
    """get_splits: COUNT the input, carve LIMIT/OFFSET windows
    (DBInputFormat.java:339, :114-115)."""

    def __init__(self, conf: Any = None) -> None:
        self.conf = conf

    def get_splits(self, conf: Any, num_splits: int) -> "list[DBSplit]":
        if (num_splits or 1) > 1 and not (conf.get(INPUT_ORDER_KEY)
                                          or conf.get(INPUT_QUERY_KEY)):
            # pure-conf check BEFORE paying the COUNT scan
            raise ValueError(
                f"{num_splits} splits over an UNORDERED table would "
                f"read overlapping/missing rows (LIMIT/OFFSET windows "
                f"are only a partition of an ordered query) — set "
                f"{INPUT_ORDER_KEY}, order {INPUT_QUERY_KEY} yourself, "
                f"or use one split")
        conn = db_connect(conf)
        try:
            count_sql = conf.get(INPUT_COUNT_QUERY_KEY)
            if not count_sql:
                # the derived-table alias is required by MySQL (error
                # 1248) and harmless on sqlite/Postgres
                count_sql = (f"SELECT COUNT(*) FROM "
                             f"({_select_query(conf)}) AS _tpumr_count")
            cur = conn.cursor()
            cur.execute(str(count_sql))
            total = int(cur.fetchone()[0])
            cur.close()
        finally:
            conn.close()
        if total == 0:
            return []
        n = max(1, min(num_splits or 1, total))
        chunk = total // n
        splits = []
        for i in range(n):
            start = i * chunk
            length = chunk if i < n - 1 else total - start
            splits.append(DBSplit(start, length))
        return splits

    def get_record_reader(self, split: "DBSplit | InputSplit",
                          conf: Any,
                          reporter: Any = None) -> _DBRecordReader:
        return _DBRecordReader(conf, split)


class _DBRecordWriter:
    def __init__(self, conf: Any, table: str,
                 fields: "list[str]") -> None:
        self.conn = db_connect(conf)
        self.mark = db_placeholder(conf)
        cols = ", ".join(fields)
        marks = ", ".join(self.mark for _ in fields)
        self.sql = (f"INSERT INTO {table} ({cols}) VALUES ({marks})"
                    if fields else None)
        self.table = table
        self.n_fields = len(fields)
        self.rows: list = []

    def write(self, key: Any, value: Any) -> None:
        """≈ DBOutputFormat.DBRecordWriter.write: the KEY is the row
        (DBWritable); a non-None value is appended as the last column
        (convenience for (key, aggregate) reduce output)."""
        row = list(key) if isinstance(key, (tuple, list)) else [key]
        if value is not None:
            row.append(value)
        if self.n_fields and len(row) != self.n_fields:
            # fail at the offending RECORD, not as an opaque driver
            # error attributed to the whole batch at close
            raise ValueError(
                f"row width {len(row)} != {self.n_fields} declared "
                f"{OUTPUT_FIELDS_KEY} columns: {row!r}")
        self.rows.append(tuple(row))

    def abort(self) -> None:
        """Failed task: drop the buffer, commit NOTHING (the runner
        calls this instead of close() when the task raised — the
        direct-write analog of a temp file never promoted)."""
        self.rows = []
        try:
            self.conn.rollback()
        finally:
            self.conn.close()

    def close(self) -> None:
        sql = self.sql
        if sql is None and self.rows:
            marks = ", ".join(self.mark for _ in self.rows[0])
            sql = f"INSERT INTO {self.table} VALUES ({marks})"
        try:
            if self.rows:
                cur = self.conn.cursor()
                cur.executemany(sql, self.rows)
                cur.close()
            self.conn.commit()          # one transaction per task
        finally:
            self.conn.close()


class DBOutputFormat:
    """Reduce output as INSERTs ≈ DBOutputFormat.java:109-158 — one
    transaction per task (the reference committed on close too; its
    re-execution caveat applies identically and is documented in the
    module docstring)."""

    def __init__(self, conf: Any = None) -> None:
        self.conf = conf

    def check_output_specs(self, conf: Any) -> None:
        if not conf.get(OUTPUT_TABLE_KEY):
            raise ValueError(f"{OUTPUT_TABLE_KEY} not set")
        # fail at submit, not in a task: the table must exist
        conn = db_connect(conf)
        try:
            cur = conn.cursor()
            cur.execute(f"SELECT * FROM "
                        f"{_ident(str(conf.get(OUTPUT_TABLE_KEY)))} "
                        f"LIMIT 0")
            cur.close()
        finally:
            conn.close()

    def get_record_writer(self, conf: Any, work_dir: str,
                          partition: int) -> _DBRecordWriter:
        fields = conf.get(OUTPUT_FIELDS_KEY)
        return _DBRecordWriter(
            conf, _ident(str(conf.get(OUTPUT_TABLE_KEY))),
            [_ident(f) for f in str(fields).split(",")] if fields else [])
