"""Fetch coalescing: device→host transfers AND shuffle wire batching.

Two batchers live here because they exploit the same economics — a
fixed per-roundtrip cost that dwarfs small payloads, amortized by
carrying many logical fetches per wire exchange:

- :class:`DeviceFetchBatcher` coalesces concurrent tasks'
  ``jax.device_get`` calls into one tunnel roundtrip;
- :func:`coalesce_shuffle_fetches` groups a reduce's pending map-output
  queue per SOURCE ADDRESS so the ShuffleCopier pulls many small
  segments from one tracker in one ``get_map_outputs_batch`` frame
  (the small-segment regime is exactly where per-RPC overhead
  dominates the shuffle).

Device→host batching design notes:

On a tunneled/remote TPU runtime every ``jax.device_get`` of computed
arrays costs a full network roundtrip (~tens of ms) regardless of payload
size, and ONE ``device_get`` over many tasks' pytrees costs the same as
one task's (measured: 8 arrays across 4 tasks = 1 roundtrip). The
LocalJobRunner exploits that with its windowed prelaunch
(tpu_runner.prelaunch_device_maps); this module is the equivalent for the
DISTRIBUTED runtime, where a tracker's TPU-slot threads run tasks
concurrently and each would otherwise pay its own roundtrip.

Design: rotating leader, zero added latency. The first thread to fetch
becomes leader and issues its ``device_get`` immediately — no linger
sleep. Threads arriving while a roundtrip is in flight queue up; when
the leader finishes, one of the QUEUED threads becomes the next leader
and takes the whole queue as one batched ``device_get`` — the in-flight
roundtrip itself is the coalescing window. Each leader serves exactly
one batch (which always contains its own entry), so no thread is held
hostage doing other tasks' transfers after its own is done: a lone task
is never delayed, and N concurrent tasks converge to ~2 roundtrips
instead of N.

If a batched fetch fails (one task's device computation raised), the
leader retries each entry individually so the error lands on the task
that caused it — innocent tasks in the same batch must not fail.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable

from tpumr.utils import progress


def coalesce_shuffle_fetches(
        first_map: int, addr: str,
        work: "queue.Queue[tuple[float, int]]",
        addr_of: "Callable[[int], str]",
        ready_now: "Callable[[float, int], bool]",
        max_segments: int) -> "list[int]":
    """Drain the copier's pending queue for more maps served by the
    same source as ``first_map`` — the members of one batched fetch.

    One bounded pass over the queue's current content (``qsize`` at
    entry — entries other workers push concurrently are next round's
    problem): maps that are ready (``ready_now(ready_at, m)``, i.e. no
    pending hold-off or penalty) and resolve to ``addr`` join the
    batch; everything else rotates back with its stamp intact. Always
    returns at least ``[first_map]``, so the caller degrades to a
    plain single fetch when nothing coalesces."""
    members = [first_map]
    if max_segments <= 1:
        return members
    putback: "list[tuple[float, int]]" = []
    scan = work.qsize()
    while scan > 0 and len(members) < max_segments:
        scan -= 1
        try:
            item = work.get_nowait()
        except queue.Empty:
            break
        # 2-tuple (ready, m) or the size-priority 3-tuple
        # (ready, -bytes, m): readiness first, map index last
        ready, m = item[0], item[-1]
        if ready_now(ready, m) and addr_of(m) == addr:
            members.append(m)
        else:
            putback.append(item)
    for item in putback:
        work.put(item)
    return members


class DeviceFetchBatcher:
    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._pending: "list[_Slot]" = []
        self._leader_running = False
        #: observability: how many device_get roundtrips vs fetch calls
        self.roundtrips = 0
        self.fetches = 0
        self.batched = 0

    def fetch(self, tree: Any) -> Any:
        """Transfer one pytree of jax.Arrays to host, coalescing with
        concurrent callers. Returns the host pytree; re-raises the
        caller's own device error."""
        slot = _Slot(tree)
        with self._cond:
            self.fetches += 1
            self._pending.append(slot)
            while not slot.done and self._leader_running:
                self._cond.wait()
            if slot.done:
                # a previous leader's batch carried this slot
                if slot.error is not None:
                    raise slot.error
                return slot.result
            # become leader for exactly one batch — which includes this
            # slot, so leading never outlives the caller's own work
            self._leader_running = True
            batch = self._pending
            self._pending = []
            self.roundtrips += 1
            self.batched += len(batch) - 1
        try:
            self._transfer(batch)
        finally:
            with self._cond:
                self._leader_running = False
                self._cond.notify_all()
        if slot.error is not None:
            raise slot.error
        return slot.result

    def _transfer(self, batch: "list[_Slot]") -> None:
        import jax
        try:
            results = jax.device_get([s.tree for s in batch])
            progress.tick(0, f"fetch-batch-{len(batch)}")
            for s, r in zip(batch, results):
                s.result = r
                s.fulfilled = True
        except Exception:  # noqa: BLE001 — isolate the failing entry
            for s in batch:
                try:
                    s.result = jax.device_get(s.tree)
                    s.fulfilled = True
                except Exception as e:  # noqa: BLE001
                    s.error = e
                with self._cond:
                    self.roundtrips += 1
        finally:
            for s in batch:
                if not s.fulfilled and s.error is None:
                    # a BaseException (KeyboardInterrupt, SystemExit)
                    # escaped both paths — batch-mates must see a real
                    # failure, never a silent None pytree
                    s.error = RuntimeError(
                        "batched device fetch aborted before this "
                        "entry transferred")
                s.done = True


class _Slot:
    __slots__ = ("tree", "result", "error", "done", "fulfilled")

    def __init__(self, tree: Any) -> None:
        self.tree = tree
        self.result = None
        self.error: "Exception | None" = None
        self.done = False
        self.fulfilled = False


_shared = DeviceFetchBatcher()


def shared_batcher() -> DeviceFetchBatcher:
    """The process-wide batcher (one tunnel, one queue)."""
    return _shared
