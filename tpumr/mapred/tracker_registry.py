"""Striped tracker registry — the heartbeat fast path's substrate.

The master's tracker table used to live behind THE global lock, so
every heartbeat's registry touch (lookup, status store, lease stamp)
queued behind every other heartbeat's fold and scheduling work. PR 7's
scale harness measured exactly that: past ~200 trackers,
``jt_lock_wait_seconds`` p99 tracked heartbeat p99 1:1. Striping the
table N ways (``tpumr.tracker.registry.shards``, default 16) means
concurrent heartbeats from different trackers contend only when their
names hash to the same stripe — and each stripe's critical section is
a few dict/attr operations, never fold or scheduler work (those moved
to per-job and scheduler locks in the same decomposition).

All stripe locks are :class:`~tpumr.metrics.locks.InstrumentedRLock`
at rank ``RANK_TRACKERS`` feeding ONE shared wait/hold histogram pair
(``jt_lock_wait_seconds{lock=trackers}``), so stripe contention is
observable as a single series next to the global and scheduler locks.

The mapping surface (``get``/``in``/``len``/``items``/``values``)
matches the dict it replaced; cross-stripe iteration snapshots each
stripe under its own lock (per-stripe-consistent, not globally
atomic — the same guarantee status pages had under the global lock,
which could interleave with evictions between renders anyway).
"""

from __future__ import annotations

from typing import Any, Iterator

from tpumr.metrics.locks import RANK_TRACKERS, InstrumentedRLock


class TrackerRegistry:
    """Name → tracker-info table striped over N independently locked
    shards."""

    def __init__(self, shards: int = 16, wait_hist: Any = None,
                 hold_hist: Any = None) -> None:
        n = max(1, int(shards))
        self._locks = [InstrumentedRLock(wait_hist, hold_hist,
                                         name="trackers",
                                         rank=RANK_TRACKERS)
                       for _ in range(n)]
        self._tables: "list[dict[str, Any]]" = [{} for _ in range(n)]

    def bind(self, wait_hist: Any, hold_hist: Any) -> "TrackerRegistry":
        for lock in self._locks:
            lock.bind(wait_hist, hold_hist)
        return self

    def shard_of(self, name: str) -> "tuple[InstrumentedRLock, dict]":
        """The (lock, table) stripe owning ``name`` — the heartbeat
        handler works read-modify-write sequences under this lock."""
        i = hash(name) % len(self._tables)
        return self._locks[i], self._tables[i]

    # ------------------------------------------------------- mapping surface

    def get(self, name: str, default: Any = None) -> Any:
        lock, table = self.shard_of(name)
        with lock:
            return table.get(name, default)

    def put(self, name: str, info: Any) -> None:
        lock, table = self.shard_of(name)
        with lock:
            table[name] = info

    def pop(self, name: str, default: Any = None) -> Any:
        lock, table = self.shard_of(name)
        with lock:
            return table.pop(name, default)

    def __getitem__(self, name: str) -> Any:
        lock, table = self.shard_of(name)
        with lock:
            return table[name]

    def __contains__(self, name: str) -> bool:
        lock, table = self.shard_of(name)
        with lock:
            return name in table

    def __len__(self) -> int:
        total = 0
        for lock, table in zip(self._locks, self._tables):
            with lock:
                total += len(table)
        return total

    def approx_len(self) -> int:
        """Lock-free size: per-stripe ``len`` reads are GIL-atomic, so
        this is exact at any quiescent moment and off by at most the
        registrations/evictions in flight — right for scheduler
        divisors and gauges, not for correctness decisions."""
        return sum(len(table) for table in self._tables)

    def names(self) -> "list[str]":
        out: "list[str]" = []
        for lock, table in zip(self._locks, self._tables):
            with lock:
                out.extend(table)
        return out

    def values(self) -> "list[Any]":
        out: "list[Any]" = []
        for lock, table in zip(self._locks, self._tables):
            with lock:
                out.extend(table.values())
        return out

    def items(self) -> "list[tuple[str, Any]]":
        out: "list[tuple[str, Any]]" = []
        for lock, table in zip(self._locks, self._tables):
            with lock:
                out.extend(table.items())
        return out

    def __iter__(self) -> Iterator[str]:
        return iter(self.names())
