"""Device-resident OUTPUT chaining — dataflow stays in HBM across jobs.

Inputs already have the HBM split cache (tpu_runner.split_cache); this
module gives kernel OUTPUTS the same residency, so a chained pipeline
(matmul → consumer, round N → round N+1) consumes its predecessor's
output without the device→host→device tunnel roundtrip. Extends the
reference's device-binding role (pipes Application.java:162-181 pins a
binary to a device) into dataflow: what the previous kernel left on the
chip IS the next job's input.

Protocol (all host-side bookkeeping; the array never moves):

1. the TPU runner, after ``map_batch_launch``, asks the kernel for
   ``device_output_rows(state)`` — the device array whose host image the
   task's output FILE will contain — and ``offer``\\ s it under the
   attempt id (only when the job's output format claims device rows,
   so non-dense jobs can never strand HBM here);
2. the dense output writer, on close, writes the .npy part file from the
   fetched host rows, then ``claim``\\ s the device array and
   ``publish``\\ es it keyed by a CONTENT fingerprint of the written
   bytes (size + sha1 of head and tail windows) — path-independent, so
   the commit rename of part files cannot stale the key;
3. a later job staging a DenseSplit of that file computes the same
   fingerprint from an 8 KB read and, on hit, slices its row range from
   the resident array ON DEVICE — zero storage read, zero upload.

Entries live in the same per-device LRU byte budget as input splits
(``tpumr.tpu.split.cache.mb``): residency is an optimization, never a
correctness dependency — the file on storage remains the truth (the
reference's fault-tolerance stance: device state is reconstructible).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any

#: fingerprint window at each end of the file
_FP_WINDOW = 4096

_lock = threading.Lock()
#: attempt_id -> device rows awaiting the writer's claim (bounded: only
#: dense-output jobs offer, and a crashed writer's entry is evicted)
_pending: dict[str, Any] = {}
_PENDING_CAP = 16
#: flips once anything was ever published in this process: lookup()
#: returns instantly until then, so jobs that never chain pay zero
#: fingerprint reads on their cache misses
_published_any = False


def offer(attempt_id: str, rows: Any) -> None:
    with _lock:
        while len(_pending) >= _PENDING_CAP:
            _pending.pop(next(iter(_pending)))
        _pending[attempt_id] = rows


def claim(attempt_id: str) -> Any:
    with _lock:
        return _pending.pop(attempt_id, None)


def fingerprint(head: bytes, tail: bytes, size: int,
                mtime: float) -> str:
    """Cache-key identity of one written file: size + mtime + head/tail
    windows. mtime disambiguates re-runs whose output happens to share
    size and boundary bytes (rename preserves mtime, so commit
    promotion keeps the key valid); head/tail windows disambiguate
    same-mtime different content. The fingerprint only SELECTS the
    candidate — correctness comes from :func:`lookup` verifying the
    publisher's full-content sha1 on the first hit, so a boundary-alias
    file can never serve wrong data."""
    h = hashlib.sha1()
    h.update(str(size).encode())
    h.update(repr(mtime).encode())
    h.update(head)
    h.update(tail)
    return h.hexdigest()


def _cache(conf: Any, device: Any):
    from tpumr.mapred.tpu_runner import split_cache
    cache_mb = conf.get_int("tpumr.tpu.split.cache.mb", 2048)
    return split_cache(device, cache_mb * 1024 * 1024)


#: (path, size, mtime, fp) identities whose FULL content has been
#: verified against the published sha — later hits on the same on-disk
#: identity skip the verification read. _verify_locks serializes the
#: first hit per identity so parallel map tasks of one chained job
#: don't each hash the same multi-GB file.
_verified: set = set()
_verify_locks: dict = {}


def publish(conf: Any, rows: Any, file_bytes_head: bytes,
            file_bytes_tail: bytes, size: int, mtime: float,
            full_sha: "str | None" = None) -> None:
    """Register a device row-matrix as resident image of a just-written
    file (writer side — fingerprint from the in-memory bytes + the
    written file's stat mtime, which the commit rename preserves).
    ``full_sha`` is the sha1 of the COMPLETE file bytes — the writer
    holds them all — so the consumer's first hit can verify the match
    beyond the boundary windows."""
    global _published_any
    try:
        devs = list(rows.devices())
    except Exception:  # noqa: BLE001 — host array slipped through
        return
    key = ("devout", fingerprint(file_bytes_head, file_bytes_tail, size,
                                 mtime))
    _cache(conf, devs[0]).put(key, {"rows": rows, "sha": full_sha},
                              int(rows.nbytes))
    _published_any = True


def lookup(conf: Any, device: Any, fs: Any, path: str, size: int,
           mtime: float):
    """The whole-file resident array for ``path``, or None. Costs one
    8 KB read to fingerprint the file — and nothing at all until some
    job in this process has actually published an output. The FIRST hit
    per on-disk identity additionally reads the whole file and checks
    the publisher's full-content sha1: a local sequential read is far
    cheaper than the tunnel upload being skipped, and it closes the
    boundary-window aliasing hole (same size+mtime+8 KB edges, different
    middle) that probabilistic fingerprints leave open."""
    if not _published_any:
        return None
    if not conf.get_boolean("tpumr.tpu.output.cache", True):
        return None
    try:
        with fs.open(path) as f:
            head = f.read(_FP_WINDOW)
            if size > _FP_WINDOW:
                f.seek(max(_FP_WINDOW, size - _FP_WINDOW))
                tail = f.read(_FP_WINDOW)
            else:
                tail = b""
    except OSError:
        return None
    fp = fingerprint(head, tail, size, mtime)
    key = ("devout", fp)
    cache = _cache(conf, device)
    entry = cache.get(key)
    if entry is None:
        return None
    sha = entry.get("sha")
    ident = (path, size, mtime, fp)
    if sha is not None and ident not in _verified:
        with _lock:
            vlock = _verify_locks.setdefault(ident, threading.Lock())
        with vlock:
            if ident not in _verified:   # first arrival verifies; the
                try:                     # rest wait and reuse the result
                    h = hashlib.sha1()
                    with fs.open(path) as f:
                        while True:
                            chunk = f.read(1 << 20)
                            if not chunk:
                                break
                            h.update(chunk)
                except OSError:
                    return None
                if h.hexdigest() != sha:
                    return None          # alias: fall back to real read
                with _lock:
                    if len(_verified) > 4096:
                        _verified.clear()
                        _verify_locks.clear()
                    _verified.add(ident)
    return entry["rows"]


def head_tail(data: bytes) -> "tuple[bytes, bytes, int]":
    """The (head, tail, size) fingerprint inputs for in-memory bytes —
    MUST mirror :func:`lookup`'s read pattern exactly."""
    head = data[:_FP_WINDOW]
    tail = data[max(_FP_WINDOW, len(data) - _FP_WINDOW):] \
        if len(data) > _FP_WINDOW else b""
    return head, tail, len(data)
