"""MiniMRCluster — a real master + N node runners in one process.

≈ ``MiniMRCluster`` (reference: src/test/org/apache/hadoop/mapred/
MiniMRCluster.java:43 — JobTrackerRunner :67 + TaskTrackerRunner threads
:142 constructing real ``new TaskTracker(conf)`` at :207): multi-node
semantics without a cluster — real RPC over localhost ports, real
heartbeats, real shuffle transfers; fake topology via per-tracker host
names (:387-446). The backbone of the integration-test tier (SURVEY.md
§4.2) and of single-host deployments.
"""

from __future__ import annotations

from typing import Any

from tpumr.mapred.jobconf import JobConf
from tpumr.mapred.jobtracker import JobMaster
from tpumr.mapred.tasktracker import NodeRunner


class MiniMRCluster:
    def __init__(self, num_trackers: int = 2, conf: JobConf | None = None,
                 cpu_slots: int = 2, tpu_slots: int = 1,
                 tpu_devices_per_tracker: int | None = None,
                 hosts: list[str] | None = None) -> None:
        self.conf = conf or JobConf()
        self.conf.set_if_unset("tpumr.heartbeat.interval.ms", 50)
        self.conf.set_if_unset("tpumr.tracker.expiry.ms", 5000)
        self.conf.set("mapred.tasktracker.map.cpu.tasks.maximum", cpu_slots)
        self.conf.set("mapred.tasktracker.map.tpu.tasks.maximum", tpu_slots)
        self.master = JobMaster(self.conf).start()
        host, port = self.master.address
        self.trackers: list[NodeRunner] = []
        for i in range(num_trackers):
            tconf = JobConf(self.conf)
            tracker = NodeRunner(
                host, port, tconf, name=f"tracker_{i}",
                host=(hosts[i] if hosts else "127.0.0.1"),
                n_tpu_devices=tpu_devices_per_tracker)
            self.trackers.append(tracker.start())

    @property
    def master_address(self) -> str:
        host, port = self.master.address
        return f"{host}:{port}"

    def create_job_conf(self) -> JobConf:
        conf = JobConf(self.conf)
        conf.set("mapred.job.tracker", self.master_address)
        return conf

    def shutdown(self) -> None:
        for t in self.trackers:
            t.stop()
        self.master.stop()

    def __enter__(self) -> "MiniMRCluster":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.shutdown()
