"""Job history server.

≈ ``org.apache.hadoop.mapred.JobHistoryServer`` + ``HistoryViewer`` +
the webapps/history JSP tier: serves completed-job summaries and full
event streams from the history directory (JSON-lines files written by
``tpumr.mapred.history.JobHistory``).
"""

from __future__ import annotations

import os
from typing import Any

from tpumr.http import StatusHttpServer
from tpumr.mapred.history import JobHistory


def job_summary(events: list[dict]) -> dict:
    """Collapse one job's event stream into the viewer row
    (≈ HistoryViewer's analysis: submit/finish, task counts, backends)."""
    out: dict[str, Any] = {"events": len(events)}
    for ev in events:
        kind = ev.get("event")
        if kind == "JOB_SUBMITTED":
            out.update(job_id=ev.get("job_id"), name=ev.get("job_name"),
                       num_maps=ev.get("num_maps"),
                       num_reduces=ev.get("num_reduces"),
                       kernel=ev.get("kernel"), submitted_ts=ev.get("ts"))
        elif kind == "JOB_FINISHED":
            out.update(state=ev.get("state"),
                       wall_time=ev.get("wall_time"),
                       finished_cpu_maps=ev.get("finished_cpu_maps"),
                       finished_tpu_maps=ev.get("finished_tpu_maps"),
                       acceleration_factor=ev.get("acceleration_factor"),
                       error=ev.get("error"))
    return out


class JobHistoryServer:
    def __init__(self, history_dir: str, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.dir = history_dir
        #: (path, mtime) -> summary; finished-job files are immutable, so
        #: summaries are cacheable and a scrape is O(new files) not
        #: O(total historical events)
        self._summary_cache: dict[str, tuple[float, dict]] = {}
        self._http = StatusHttpServer("history", host=host, port=port)
        self._http.add_json("history", self._list)
        self._http.add_json("job", self._job, parameterized=True)
        self._http.add_page("index", self._index_page)

    def _index_page(self, q: dict) -> str:
        """Completed-jobs table ≈ webapps/history jobhistory.jsp."""
        from tpumr.http import RawHtml, html_escape, html_table
        rows = []
        for s in sorted(self._list(q),
                        key=lambda s: s.get("submitted_ts") or 0,
                        reverse=True):
            state = s.get("state", "?")
            cls = "ok" if state == "SUCCEEDED" else "bad"
            rows.append([
                s.get("job_id", "?"),
                s.get("name", ""),
                RawHtml(f"<span class='{cls}'>{html_escape(state)}</span>"),
                f"{s.get('num_maps', '?')}", f"{s.get('num_reduces', '?')}",
                f"{s.get('finished_tpu_maps', 0) or 0}",
                f"{s.get('finished_cpu_maps', 0) or 0}",
                (f"{s['wall_time']:.1f}s"
                 if s.get("wall_time") is not None else "—"),
            ])
        return ("<h1>Job History</h1>" + html_table(
            ["job", "name", "state", "#maps", "#reduces", "tpu maps",
             "cpu maps", "wall time"], rows))

    def _files(self) -> dict[str, str]:
        if not os.path.isdir(self.dir):
            return {}
        return {f[:-len(".jsonl")]: os.path.join(self.dir, f)
                for f in sorted(os.listdir(self.dir))
                if f.endswith(".jsonl")}

    def _list(self, q: dict) -> list[dict]:
        out = []
        for _job, path in self._files().items():
            mtime = os.path.getmtime(path)
            cached = self._summary_cache.get(path)
            if cached is None or cached[0] != mtime:
                cached = (mtime, job_summary(JobHistory.read(path)))
                self._summary_cache[path] = cached
            out.append(cached[1])
        return out

    def _job(self, q: dict) -> Any:
        path = self._files().get(q.get("id", ""))
        if path is None:
            return {"error": f"no history for job {q.get('id')!r}",
                    "known": sorted(self._files())}
        return [self._redact(ev) for ev in JobHistory.read(path)]

    @staticmethod
    def _redact(event: dict) -> dict:
        """History files keep the full submission conf (the restarted
        master needs it to replay jobs), but the status port must not
        serve credential values (≈ ConfServlet sanitization) — the
        JOB_SUBMITTED conf can carry tpumr.rpc.secret."""
        conf = event.get("conf")
        if not isinstance(conf, dict):
            return event
        from tpumr.core.configuration import redact_mapping
        event = dict(event)
        event["conf"] = redact_mapping(conf)
        return event

    # ------------------------------------------------------------ lifecycle

    @property
    def url(self) -> str:
        return self._http.url

    def start(self) -> "JobHistoryServer":
        self._http.start()
        return self

    def stop(self) -> None:
        self._http.stop()
