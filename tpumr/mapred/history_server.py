"""Job history server.

≈ ``org.apache.hadoop.mapred.JobHistoryServer`` + ``HistoryViewer`` +
the webapps/history JSP tier: serves completed-job summaries and full
event streams from the history directory (JSON-lines files written by
``tpumr.mapred.history.JobHistory``).
"""

from __future__ import annotations

import os
from typing import Any

from tpumr.http import StatusHttpServer
from tpumr.mapred.history import JobHistory


def job_summary(events: list[dict]) -> dict:
    """Collapse one job's event stream into the viewer row
    (≈ HistoryViewer's analysis: submit/finish, task counts, backends)."""
    out: dict[str, Any] = {"events": len(events)}
    for ev in events:
        kind = ev.get("event")
        if kind == "JOB_SUBMITTED":
            out.update(job_id=ev.get("job_id"), name=ev.get("job_name"),
                       num_maps=ev.get("num_maps"),
                       num_reduces=ev.get("num_reduces"),
                       kernel=ev.get("kernel"), submitted_ts=ev.get("ts"),
                       priority=ev.get("priority", "NORMAL"))
        elif kind == "JOB_PRIORITY_CHANGED":
            # the queue can be re-ordered live (job -set-priority); the
            # viewer must show the priority the job actually ran at
            out["priority"] = ev.get("priority", out.get("priority"))
        elif kind == "JOB_FINISHED":
            out.update(state=ev.get("state"),
                       wall_time=ev.get("wall_time"),
                       finished_cpu_maps=ev.get("finished_cpu_maps"),
                       finished_tpu_maps=ev.get("finished_tpu_maps"),
                       acceleration_factor=ev.get("acceleration_factor"),
                       placement=ev.get("placement"),
                       error=ev.get("error"))
    return out


def task_timeline(events: list[dict]) -> list[dict]:
    """Per-attempt rows merged from TASK_STARTED + terminal events —
    the data behind the drill-down table and timeline (the role of the
    reference's jobtasks.jsp/taskdetails.jsp tables and
    ``TaskGraphServlet``'s progress graph, src/mapred/org/apache/hadoop/
    mapred/TaskGraphServlet.java — placement is first-class here where
    the reference had no backend column at all)."""
    rows: dict[str, dict] = {}
    for ev in events:
        kind = ev.get("event")
        aid = ev.get("attempt_id")
        if not aid:
            continue
        row = rows.setdefault(aid, {"attempt_id": aid})
        if kind == "TASK_STARTED":
            row.update(start_ts=ev.get("ts"), is_map=ev.get("is_map"),
                       run_on_tpu=ev.get("run_on_tpu"),
                       tpu_device_id=ev.get("tpu_device_id"),
                       tracker=ev.get("tracker"))
        elif kind in ("TASK_FINISHED", "TASK_FAILED", "TASK_KILLED"):
            row.update(state=kind[len("TASK_"):], finish_ts=ev.get("ts"),
                       runtime=ev.get("runtime"),
                       is_map=ev.get("is_map", row.get("is_map")),
                       run_on_tpu=ev.get("run_on_tpu",
                                         row.get("run_on_tpu")),
                       tpu_device_id=ev.get("tpu_device_id",
                                            row.get("tpu_device_id")),
                       tracker=ev.get("tracker", row.get("tracker")),
                       counters=ev.get("counters"))
            # attempts recovered from a pre-restart log may miss their
            # TASK_STARTED: derive start from finish - runtime
            if row.get("start_ts") is None and ev.get("ts") is not None \
                    and ev.get("runtime") is not None:
                row["start_ts"] = ev["ts"] - ev["runtime"]
    out = sorted(rows.values(), key=lambda r: (r.get("start_ts") or 0,
                                               r["attempt_id"]))
    for r in out:
        r.setdefault("state", "RUNNING")
        if r.get("runtime") is None and r.get("start_ts") is not None \
                and r.get("finish_ts") is not None:
            r["runtime"] = r["finish_ts"] - r["start_ts"]
    return out


def _backend_label(t: dict) -> str:
    """Placement label shared by the SVG rows and the attempts table —
    one definition so the two views can't drift."""
    if not t.get("is_map"):
        return "reduce"
    return f"tpu:{t.get('tpu_device_id')}" if t.get("run_on_tpu") \
        else "cpu"


def placement_svg(placement: dict, width: int = 600) -> str:
    """Inline-SVG convergence curve: cumulative TPU share of map
    assignments vs assignment index (the plot VERDICT r4 #9 asked the
    history to carry — optional scheduling shows as the share climbing
    to 1.0 mid-job as the starvation rule fires,
    ≈ JobQueueTaskScheduler.java:290-327)."""
    seq = (placement or {}).get("seq") or ""
    if len(seq) < 2:
        return ""
    h, pad = 80, 14
    tpu = 0
    pts = []
    for i, b in enumerate(seq):
        tpu += (b == "T")
        x = pad + i / (len(seq) - 1) * (width - 2 * pad)
        y = h - pad - (tpu / (i + 1)) * (h - 2 * pad)
        pts.append(f"{x:.1f},{y:.1f}")
    share = tpu / len(seq)
    return (
        f"<h2>Placement convergence</h2>"
        f"<svg viewBox='0 0 {width} {h}' width='{width}' "
        f"xmlns='http://www.w3.org/2000/svg' role='img' "
        f"style='font:10px monospace'>"
        f"<line x1='{pad}' y1='{h - pad}' x2='{width - pad}' "
        f"y2='{h - pad}' stroke='#888888'/>"
        f"<line x1='{pad}' y1='{pad}' x2='{pad}' y2='{h - pad}' "
        f"stroke='#888888'/>"
        f"<polyline points='{' '.join(pts)}' fill='none' "
        f"stroke='#7f5af0' stroke-width='1.5'/>"
        f"<text x='{pad + 4}' y='{pad}' fill='currentColor'>"
        f"cumulative TPU share of map assignments "
        f"(final {share:.0%}, n={len(seq)})</text></svg>")


def timeline_svg(tasks: list[dict], width: int = 900) -> str:
    """Inline-SVG Gantt of one job's attempts, colored by backend —
    the TaskGraphServlet drawing, redrawn for the hybrid story: the
    convergence signature (CPU rows early, an all-TPU tail) is visible
    at a glance."""
    from tpumr.http import html_escape
    spans = [t for t in tasks if t.get("start_ts") is not None]
    if not spans:
        return "<p class='dim'>no timeline data in this job's events</p>"
    t0 = min(t["start_ts"] for t in spans)
    t1 = max((t.get("finish_ts") or t["start_ts"]) for t in spans)
    span = max(t1 - t0, 1e-6)
    rh, gap, left = 16, 4, 230
    h = len(spans) * (rh + gap) + 24
    parts = [f"<svg viewBox='0 0 {width} {h}' width='100%' "
             f"xmlns='http://www.w3.org/2000/svg' role='img' "
             f"style='font:11px monospace'>"]
    for i, t in enumerate(spans):
        y = i * (rh + gap) + 18
        x0 = left + (t["start_ts"] - t0) / span * (width - left - 10)
        x1 = left + ((t.get("finish_ts") or t1) - t0) / span \
            * (width - left - 10)
        color = ("#7f5af0" if t.get("run_on_tpu") else "#2cb67d") \
            if t.get("state") == "FINISHED" else \
            ("#e45858" if t.get("state") in ("FAILED", "KILLED")
             else "#888888")
        label = t["attempt_id"]
        backend = _backend_label(t)
        parts.append(
            f"<text x='0' y='{y + rh - 4}' fill='currentColor'>"
            f"{html_escape(label)} [{html_escape(backend)}]</text>")
        parts.append(
            f"<rect x='{x0:.1f}' y='{y}' "
            f"width='{max(x1 - x0, 2):.1f}' height='{rh}' rx='2' "
            f"fill='{color}'><title>{html_escape(label)} "
            f"{html_escape(backend)} {t.get('runtime') or 0:.2f}s "
            f"{html_escape(t.get('state', ''))}</title></rect>")
    parts.append(
        f"<text x='{left}' y='12' fill='currentColor'>"
        f"0s … {span:.2f}s &#160; "
        f"<tspan fill='#7f5af0'>&#9632; tpu</tspan> "
        f"<tspan fill='#2cb67d'>&#9632; cpu</tspan> "
        f"<tspan fill='#e45858'>&#9632; failed</tspan></text>")
    parts.append("</svg>")
    return "".join(parts)


class JobHistoryServer:
    def __init__(self, history_dir: str, host: str = "127.0.0.1",
                 port: int = 0, conf: Any = None) -> None:
        self.dir = history_dir
        #: (path, mtime) -> summary; finished-job files are immutable, so
        #: summaries are cacheable and a scrape is O(new files) not
        #: O(total historical events)
        self._summary_cache: dict[str, tuple[float, dict]] = {}
        self._http = StatusHttpServer("history", host=host, port=port)
        # continuous profiler (conf-gated, same knob as every daemon)
        self.sampler = None
        if conf is not None:
            from tpumr.metrics.sampler import StackSampler
            self.sampler = StackSampler.from_conf(conf)
            if self.sampler is not None:
                self.sampler.attach_http(self._http)
        self._http.add_json("history", self._list)
        self._http.add_json("job", self._job, parameterized=True)
        self._http.add_json("tasks", self._tasks, parameterized=True)
        self._http.add_page("index", self._index_page)
        self._http.add_page("jobtasks", self._jobtasks_page,
                            parameterized=True)

    def _index_page(self, q: dict) -> str:
        """Completed-jobs table ≈ webapps/history jobhistory.jsp."""
        from tpumr.http import RawHtml, html_escape, html_table
        rows = []
        for s in sorted(self._list(q),
                        key=lambda s: s.get("submitted_ts") or 0,
                        reverse=True):
            state = s.get("state", "?")
            cls = "ok" if state == "SUCCEEDED" else "bad"
            jid = s.get("job_id", "?")
            rows.append([
                RawHtml(f"<a href='/jobtasks?id={html_escape(jid)}'>"
                        f"{html_escape(jid)}</a>"),
                s.get("name", ""),
                RawHtml(f"<span class='{cls}'>{html_escape(state)}</span>"),
                f"{s.get('num_maps', '?')}", f"{s.get('num_reduces', '?')}",
                f"{s.get('finished_tpu_maps', 0) or 0}",
                f"{s.get('finished_cpu_maps', 0) or 0}",
                (f"{s['wall_time']:.1f}s"
                 if s.get("wall_time") is not None else "—"),
            ])
        return ("<h1>Job History</h1>" + html_table(
            ["job", "name", "state", "#maps", "#reduces", "tpu maps",
             "cpu maps", "wall time"], rows))

    def _files(self) -> dict[str, str]:
        if not os.path.isdir(self.dir):
            return {}
        return {f[:-len(".jsonl")]: os.path.join(self.dir, f)
                for f in sorted(os.listdir(self.dir))
                if f.endswith(".jsonl")}

    def _list(self, q: dict) -> list[dict]:
        out = []
        for _job, path in self._files().items():
            mtime = os.path.getmtime(path)
            cached = self._summary_cache.get(path)
            if cached is None or cached[0] != mtime:
                cached = (mtime, job_summary(JobHistory.read(path)))
                self._summary_cache[path] = cached
            out.append(cached[1])
        return out

    def _job(self, q: dict) -> Any:
        path = self._files().get(q.get("id", ""))
        if path is None:
            return {"error": f"no history for job {q.get('id')!r}",
                    "known": sorted(self._files())}
        return [self._redact(ev) for ev in JobHistory.read(path)]

    def _tasks(self, q: dict) -> Any:
        """Per-attempt drill-down rows (timings, tracker, placement)."""
        path = self._files().get(q.get("id", ""))
        if path is None:
            return {"error": f"no history for job {q.get('id')!r}"}
        return task_timeline(JobHistory.read(path))

    def _jobtasks_page(self, q: dict) -> str:
        """Task table + backend-colored timeline for one finished job
        (≈ jobtasks.jsp/taskdetails.jsp + TaskGraphServlet)."""
        from tpumr.http import RawHtml, html_escape, html_table
        jid = q.get("id", "")
        path = self._files().get(jid)
        if path is None:
            return (f"<h1>Unknown job {html_escape(jid)}</h1>"
                    "<p><a href='/index'>back</a></p>")
        events = JobHistory.read(path)
        summary = job_summary(events)
        tasks = task_timeline(events)
        rows = []
        from tpumr.core.counters import TaskCounter
        for t in tasks:
            cls = {"FINISHED": "ok", "FAILED": "bad",
                   "KILLED": "bad"}.get(t.get("state", ""), "dim")
            shuffled = (t.get("counters") or {}).get(
                TaskCounter.FRAMEWORK_GROUP, {}).get(
                TaskCounter.REDUCE_SHUFFLE_BYTES)
            rows.append([
                t["attempt_id"],
                RawHtml(f"<span class='{cls}'>"
                        f"{html_escape(t.get('state', '?'))}</span>"),
                _backend_label(t),
                t.get("tracker") or "—",
                (f"{t['runtime']:.2f}s"
                 if t.get("runtime") is not None else "—"),
                (f"{shuffled:,}" if shuffled is not None else "—"),
            ])
        name = summary.get("name") or ""
        return (
            f"<h1>Tasks — {html_escape(jid)}</h1>"
            f"<p>{html_escape(name)} · state "
            f"<b>{html_escape(str(summary.get('state', '?')))}</b> · "
            f"{summary.get('num_maps', '?')} maps / "
            f"{summary.get('num_reduces', '?')} reduces · accel "
            f"{summary.get('acceleration_factor') or '—'}</p>"
            + placement_svg(summary.get("placement") or {})
            + f"<h2>Timeline</h2>" + timeline_svg(tasks)
            + f"<h2>Attempts ({len(rows)})</h2>"
            + html_table(["attempt", "state", "backend", "tracker",
                          "runtime", "shuffle bytes"], rows)
            + "<p><a href='/index'>« job list</a> · "
            + f"<a href='/job?id={html_escape(jid)}'>raw events</a></p>")

    @staticmethod
    def _redact(event: dict) -> dict:
        """History files keep the full submission conf (the restarted
        master needs it to replay jobs), but the status port must not
        serve credential values (≈ ConfServlet sanitization) — the
        JOB_SUBMITTED conf can carry tpumr.rpc.secret."""
        conf = event.get("conf")
        if not isinstance(conf, dict):
            return event
        from tpumr.core.configuration import redact_mapping
        event = dict(event)
        event["conf"] = redact_mapping(conf)
        return event

    # ------------------------------------------------------------ lifecycle

    @property
    def url(self) -> str:
        return self._http.url

    def start(self) -> "JobHistoryServer":
        self._http.start()
        if self.sampler is not None:
            self.sampler.start()
        return self

    def stop(self) -> None:
        if self.sampler is not None:
            self.sampler.stop()
        self._http.stop()
