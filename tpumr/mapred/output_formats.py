"""Output formats + the two-phase output commit protocol.

≈ ``org.apache.hadoop.mapred.{OutputFormat,TextOutputFormat,
SequenceFileOutputFormat,FileOutputCommitter}``. The commit protocol is the
reference's (FileOutputCommitter semantics, gated by the tracker's
CommitTaskAction, mapred/TaskTracker.java:1725-1731): tasks write to
``$out/_temporary/<attempt>/``; a successful attempt's dir is atomically
promoted into ``$out``; job commit writes ``_SUCCESS`` and removes
``_temporary`` — so re-executed/speculative attempts never corrupt output.
"""

from __future__ import annotations

from typing import Any

from tpumr.fs.filesystem import FileSystem, Path
from tpumr.io import sequencefile

TEMP_DIR = "_temporary"
SUCCESS_MARKER = "_SUCCESS"


def part_name(partition: int, prefix: str = "part") -> str:
    return f"{prefix}-{partition:05d}"


class RecordWriter:
    def write(self, key: Any, value: Any) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class OutputFormat:
    def get_record_writer(self, conf: Any, work_dir: str,
                          partition: int,
                          prefix: str = "part") -> RecordWriter:
        """``prefix`` names side outputs (lib.MultipleOutputs): the
        default "part" is the job's main output stream."""
        raise NotImplementedError

    def check_output_specs(self, conf: Any) -> None:
        """≈ OutputFormat.checkOutputSpecs: refuse to clobber existing
        output (FileOutputFormat throws FileAlreadyExistsException)."""
        out = conf.get("mapred.output.dir")
        if not out:
            raise ValueError("mapred.output.dir not set")
        fs = FileSystem.get(out, conf)
        # any non-empty existing output dir is refused — including leftovers
        # of a crashed run (FileOutputFormat.checkOutputSpecs throws
        # FileAlreadyExistsException on mere existence; we allow an empty dir)
        if fs.exists(out) and (not fs.get_status(out).is_dir
                               or fs.list_status(out)):
            raise FileExistsError(f"output directory already exists: {out}")


class _TextWriter(RecordWriter):
    def __init__(self, stream, separator: str = "\t") -> None:
        self._f = stream
        self._sep = separator.encode()

    def write(self, key: Any, value: Any) -> None:
        def enc(x: Any) -> bytes:
            if isinstance(x, bytes):
                return x
            return str(x).encode("utf-8")
        if key is None:
            self._f.write(enc(value) + b"\n")
        else:
            self._f.write(enc(key) + self._sep + enc(value) + b"\n")

    def close(self) -> None:
        self._f.close()


class TextOutputFormat(OutputFormat):
    """≈ org.apache.hadoop.mapred.TextOutputFormat: key<TAB>value lines."""

    def get_record_writer(self, conf, work_dir, partition,
                          prefix="part"):
        fs = FileSystem.get(work_dir, conf)
        sep = conf.get("mapred.textoutputformat.separator", "\t")
        f = fs.create(Path(work_dir).child(part_name(partition, prefix)))
        return _TextWriter(f, sep)


class _SeqWriter(RecordWriter):
    def __init__(self, stream, codec: str) -> None:
        self._f = stream
        self._w = sequencefile.Writer(stream, codec=codec)

    def write(self, key: Any, value: Any) -> None:
        self._w.append(key, value)

    def write_fixed_rows(self, rows, klen: int) -> None:
        """Bulk path for fixed-width byte records (device-shuffled reduce):
        one numpy tile job instead of n append() calls."""
        self._w.append_fixed_rows(rows, klen)

    def close(self) -> None:
        self._w.close()
        self._f.close()


class SequenceFileOutputFormat(OutputFormat):
    def get_record_writer(self, conf, work_dir, partition,
                          prefix="part"):
        fs = FileSystem.get(work_dir, conf)
        codec = conf.get("mapred.output.compression.codec", "none") \
            if conf.get_boolean("mapred.output.compress", False) else "none"
        f = fs.create(Path(work_dir).child(part_name(partition, prefix)))
        return _SeqWriter(f, codec)


class _DenseNpyWriter(RecordWriter):
    """Collects (row0, block) map outputs into ONE .npy part file, then
    hands the written bytes' fingerprint to the device-output cache so a
    chained job can consume this file straight from HBM
    (tpumr/mapred/device_output.py)."""

    def __init__(self, conf: Any, fs: Any, path) -> None:
        self._conf = conf
        self._fs = fs
        self._path = path
        self._blocks: "list[tuple[int, Any]]" = []

    def write(self, key: Any, value: Any) -> None:
        import numpy as np
        self._blocks.append((int(key), np.asarray(value)))

    def close(self) -> None:
        import io as _io

        import numpy as np
        self._blocks.sort(key=lambda b: b[0])
        arr = (np.concatenate([b[1] for b in self._blocks])
               if self._blocks else np.zeros((0, 0), np.float32))
        buf = _io.BytesIO()
        np.save(buf, np.ascontiguousarray(arr))
        data = buf.getvalue()
        with self._fs.create(self._path) as f:
            f.write(data)
        # publish the device-resident image, if the kernel offered one;
        # the written file's mtime is part of the key (rename-stable)
        from tpumr.mapred import device_output
        rows = device_output.claim(
            str(self._conf.get("tpumr.task.attempt.id", "")))
        if (rows is not None
                and getattr(rows, "shape", None) == arr.shape
                and str(getattr(rows, "dtype", "")) == str(arr.dtype)):
            head, tail, size = device_output.head_tail(data)
            try:
                mtime = self._fs.get_status(self._path).mtime
            except OSError:
                return
            import hashlib
            device_output.publish(self._conf, rows, head, tail, size,
                                  mtime,
                                  full_sha=hashlib.sha1(data).hexdigest())


class DenseNpyOutputFormat(OutputFormat):
    """Map-side dense output: records are ``(row0, 2-D block)``; each
    task writes ``part-N.npy``. The TPU-first leg of output chaining —
    a later DenseInputFormat job over this directory stages cached
    blocks directly from HBM. New design (no reference equivalent: the
    closest is SequenceFile of serialized blocks)."""

    #: tpu_runner gates device_output.offer on this marker
    claims_device_rows = True

    def get_record_writer(self, conf, work_dir, partition, prefix="part"):
        fs = FileSystem.get(work_dir, conf)
        return _DenseNpyWriter(
            conf, fs, Path(work_dir).child(part_name(partition, prefix)
                                           + ".npy"))


class _NullWriter(RecordWriter):
    def write(self, key: Any, value: Any) -> None:
        pass


class NullOutputFormat(OutputFormat):
    """≈ mapred/lib/NullOutputFormat.java — discards output."""

    def get_record_writer(self, conf, work_dir, partition, prefix="part"):
        return _NullWriter()

    def check_output_specs(self, conf) -> None:
        pass


class FileOutputCommitter:
    """≈ org.apache.hadoop.mapred.FileOutputCommitter."""

    def __init__(self, conf: Any) -> None:
        self.out = conf.get("mapred.output.dir")
        self.fs = FileSystem.get(self.out, conf) if self.out else None
        self.conf = conf

    # job lifecycle

    def setup_job(self) -> None:
        if self.fs:
            self.fs.mkdirs(Path(self.out).child(TEMP_DIR))

    def commit_job(self) -> None:
        if self.fs:
            self.fs.delete(Path(self.out).child(TEMP_DIR), recursive=True)
            self.fs.write_bytes(Path(self.out).child(SUCCESS_MARKER), b"")

    def abort_job(self) -> None:
        if self.fs:
            self.fs.delete(Path(self.out).child(TEMP_DIR), recursive=True)

    # task lifecycle

    def work_dir(self, attempt_id: str) -> str:
        return str(Path(self.out).child(TEMP_DIR).child(str(attempt_id)))

    def setup_task(self, attempt_id: str) -> str:
        # no output dir (NullOutputFormat jobs): nothing to stage or commit
        if self.fs is None:
            return ""
        wd = self.work_dir(attempt_id)
        self.fs.mkdirs(wd)
        return wd

    def needs_commit(self, attempt_id: str) -> bool:
        if self.fs is None:
            return False
        wd = self.work_dir(attempt_id)
        return self.fs.exists(wd) and bool(self.fs.list_files(wd))

    def commit_task(self, attempt_id: str) -> None:
        """Promote the attempt dir's files into $out (first writer wins per
        name — speculative duplicates are dropped, matching the reference's
        single-CommitTaskAction gate)."""
        wd = self.work_dir(attempt_id)
        if not self.fs.exists(wd):
            return
        for st in self.fs.list_files(wd, recursive=True):
            dst = Path(self.out).child(st.path.name)
            if not self.fs.exists(dst):
                self.fs.rename(st.path, dst)
        self.fs.delete(wd, recursive=True)

    def abort_task(self, attempt_id: str) -> None:
        self.fs.delete(self.work_dir(attempt_id), recursive=True)
