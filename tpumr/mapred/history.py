"""Job history — structured event log.

≈ ``org.apache.hadoop.mapred.JobHistory`` (reference: src/mapred/org/apache/
hadoop/mapred/JobHistory.java, 2703 LoC — field-encoded line format parsed
by HistoryViewer/rumen). Re-designed as JSON-lines per job under
``tpumr.history.dir`` (one self-describing event per line), which serves the
same consumers: post-hoc job analysis, the web status JSON, and recovery
replay. Backend placement is a first-class field on every task event —
the reference's GPU observability was log-grep only (SURVEY.md §5).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any


def _json_safe(v: Any) -> bool:
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False


class JobHistory:
    """Event-log writer. By default (``tpumr.history.async``) events are
    stamped at enqueue time and appended by one daemon writer thread off
    a bounded queue — the heartbeat's deferred phase pays a list append,
    never an fsync-adjacent ``open``/``write``. The queue is bounded
    (``tpumr.history.queue.max``); past the bound events are DROPPED and
    counted (``history_writes_dropped`` — a bench run must keep it 0).
    Recovery readers call :meth:`flush` first, so replay always sees
    every event the master logged before the read."""

    def __init__(self, conf: Any) -> None:
        self.dir = conf.get("tpumr.history.dir") if conf else None
        self._lock = threading.Lock()
        self._async = bool(conf.get_boolean("tpumr.history.async", True)
                           if conf else True)
        self._queue_max = int(conf.get_int("tpumr.history.queue.max",
                                           10_000) if conf else 10_000)
        self._cv = threading.Condition()
        self._queue: "list[tuple[str, dict]]" = []
        self._writing = False     # drain batch in flight (flush waits)
        self._stopped = False
        self._writer: "threading.Thread | None" = None
        self.writes_dropped = 0   # bound into metrics by the master

    # ------------------------------------------------------ write path

    def _write(self, job_id: str, event: dict) -> None:
        if not self.dir:
            return
        event["ts"] = time.time()   # stamped at ENQUEUE: event time,
        #                             not whenever the writer drains
        if not self._async:
            self._write_now([(job_id, event)])
            return
        with self._cv:
            if not self._stopped:
                if len(self._queue) >= self._queue_max:
                    self.writes_dropped += 1
                    return
                self._queue.append((job_id, event))
                if self._writer is None:
                    self._writer = threading.Thread(
                        target=self._drain, name="history-writer",
                        daemon=True)
                    self._writer.start()
                self._cv.notify_all()
                return
        # post-stop stragglers (late finalization racing shutdown)
        # write synchronously so nothing is silently lost
        self._write_now([(job_id, event)])

    def _write_now(self, batch: "list[tuple[str, dict]]") -> None:
        """Append a batch, one ``open`` per job file (per-file order is
        the enqueue order; cross-file order carries no meaning)."""
        by_job: "dict[str, list[str]]" = {}
        for job_id, event in batch:
            by_job.setdefault(job_id, []).append(
                json.dumps(event) + "\n")
        os.makedirs(self.dir, exist_ok=True)
        with self._lock:
            for job_id, lines in by_job.items():
                with open(os.path.join(self.dir,
                                       f"{job_id}.jsonl"), "a") as f:
                    f.write("".join(lines))

    def _drain(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stopped:
                    self._cv.wait(0.5)
                batch, self._queue = self._queue, []
                stopped = self._stopped
                self._writing = bool(batch)
            if batch:
                try:
                    self._write_now(batch)
                except OSError:
                    self.writes_dropped += len(batch)
                with self._cv:
                    self._writing = False
                    self._cv.notify_all()
            if stopped and not batch:
                return

    def queue_depth(self) -> int:
        return len(self._queue) + (1 if self._writing else 0)

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until every enqueued event is on disk (readers that
        replay the log — recovery, retired-status serving — call this
        first). True when the queue fully drained."""
        if not self._async or not self.dir:
            return True
        deadline = time.monotonic() + timeout_s
        with self._cv:
            self._cv.notify_all()
            while self._queue or self._writing:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(0.05, left))
        return True

    def stop(self, timeout_s: float = 10.0) -> None:
        """Flush and retire the writer thread (master shutdown). The
        log must be complete on disk before ``stop()`` returns — a
        restart immediately replays it."""
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
            writer = self._writer
        if writer is not None:
            writer.join(timeout=timeout_s)

    def job_submitted(self, jip: Any) -> None:
        self._write(str(jip.job_id), {
            "event": "JOB_SUBMITTED",
            "job_id": str(jip.job_id),
            "job_name": jip.conf.get("mapred.job.name", ""),
            "num_maps": jip.num_maps,
            "num_reduces": jip.num_reduces,
            "kernel": jip.conf.get("tpumr.map.kernel"),
            "priority": jip.priority,
            # full submission payload so a restarted master can replay the
            # job (≈ RecoveryManager reading the job-info staging file)
            "conf": {k: v for k, v in jip.conf.items()
                     if _json_safe(v)},
            # keys whose values can't ride the wire (in-process class
            # objects): recovery refuses to replay such jobs rather than
            # resubmitting them broken
            "conf_dropped": sorted(k for k, v in jip.conf.items()
                                   if not _json_safe(v)),
            "splits": [t.split for t in jip.maps],
        })

    def job_recovered(self, old_job_id: str, new_job_id: str) -> None:
        """Marks the interrupted job as resubmitted (so a second restart
        doesn't replay it again)."""
        self._write(old_job_id, {"event": "JOB_RECOVERED",
                                 "job_id": old_job_id,
                                 "new_job_id": new_job_id})

    def incomplete_jobs(self) -> list[dict]:
        """JOB_SUBMITTED events of jobs with no terminal/recovered marker —
        the restart-recovery work list (≈ RecoveryManager.recover,
        JobTracker.java:1203)."""
        import glob
        if not self.dir:
            return []
        self.flush()
        out = []
        for path in sorted(glob.glob(os.path.join(self.dir, "*.jsonl"))):
            submitted = None
            finished = False
            priority = None
            for ev in self.read(path):
                kind = ev.get("event")
                if kind == "JOB_SUBMITTED":
                    submitted = ev
                elif kind in ("JOB_FINISHED", "JOB_RECOVERED",
                              "JOB_RECOVERY_FAILED"):
                    finished = True
                elif kind == "JOB_PRIORITY_CHANGED":
                    priority = ev.get("priority")
            if submitted is not None and not finished \
                    and submitted.get("conf") is not None:
                if priority:
                    # replay runtime priority changes into the conf the
                    # recovery resubmits — a restart must not silently
                    # revert `job -set-priority`
                    submitted["conf"]["mapred.job.priority"] = priority
                out.append(submitted)
        return out

    def incomplete_pipelines(self) -> "list[dict]":
        """PIPELINE_SUBMITTED records (full graph payload) of pipelines
        with no terminal marker, plus their replayed stage submissions
        — the pipeline half of restart recovery. A PIPELINE_RECOVERED
        marker does NOT finish the file: the pipeline keeps its id
        across restarts and a second crash replays it again (stage-job
        aliasing is the jobs' problem, handled by the caller)."""
        import glob
        if not self.dir:
            return []
        self.flush()
        out = []
        for path in sorted(glob.glob(os.path.join(self.dir,
                                                  "pipe_*.jsonl"))):
            submitted = None
            finished = False
            stages: "list[dict]" = []
            for ev in self.read(path):
                kind = ev.get("event")
                if kind == "PIPELINE_SUBMITTED":
                    submitted = ev
                elif kind in ("PIPELINE_FINISHED",
                              "PIPELINE_RECOVERY_FAILED"):
                    finished = True
                elif kind == "PIPELINE_STAGE_SUBMITTED":
                    stages.append(ev)
            if submitted is not None and not finished \
                    and submitted.get("graph"):
                out.append({"pipeline_id": submitted["pipeline_id"],
                            "graph": submitted["graph"],
                            "user": submitted.get("user", ""),
                            "stages": stages})
        return out

    def recovered_attempt_state(self, job_id: str) -> dict:
        """Replay one interrupted job's attempt-level outcome from its
        event log (≈ RecoveryManager.JobRecoveryListener walking the
        history file): the LAST successful attempt per task, with the
        detail a restarted master needs to adopt the work instead of
        re-running it — attempt id, serving tracker + shuffle address
        (map outputs), backend, runtime, and counters. ``MAP_OUTPUT_LOST``
        events (fetch-failure withdrawals, lost trackers) erase the
        outputs the old master already declared gone. Returns
        ``{"maps": {partition: record}, "reduces": {partition: record}}``.
        """
        from tpumr.mapred.ids import TaskAttemptID
        maps: dict[int, dict] = {}
        reduces: dict[int, dict] = {}
        if not self.dir:
            return {"maps": maps, "reduces": reduces}
        self.flush()
        path = os.path.join(self.dir, f"{job_id}.jsonl")
        if not os.path.exists(path):
            return {"maps": maps, "reduces": reduces}
        for ev in self.read(path):
            kind = ev.get("event")
            aid = str(ev.get("attempt_id", "") or "")
            if not aid:
                continue
            try:
                attempt = TaskAttemptID.parse(aid)
            except (ValueError, IndexError):
                continue
            idx = attempt.task.id
            if kind == "TASK_FINISHED":
                rec = {
                    "attempt_id": aid,
                    "attempt": attempt.attempt,
                    "is_map": bool(attempt.task.is_map),
                    "runtime": float(ev.get("runtime", 0.0) or 0.0),
                    "tracker": ev.get("tracker", ""),
                    "shuffle_addr": ev.get("shuffle_addr", "") or "",
                    "run_on_tpu": bool(ev.get("run_on_tpu", False)),
                    "tpu_device_id": int(ev.get("tpu_device_id", -1)),
                    "counters": ev.get("counters") or {},
                    "ts": float(ev.get("ts", 0.0) or 0.0),
                }
                (maps if attempt.task.is_map else reduces)[idx] = rec
            elif kind == "MAP_OUTPUT_LOST":
                # the old master withdrew this output (too many fetch
                # failures, or its tracker was lost) — whatever replaced
                # it appears as a LATER TASK_FINISHED, or not at all
                cur = maps.get(idx)
                if cur is not None and cur["attempt_id"] == aid:
                    del maps[idx]
        return {"maps": maps, "reduces": reduces}

    def retired_job_status(self, job_id: str) -> "dict | None":
        """Terminal status of a job known only to HISTORY — a restarted
        master serving polls for jobs that finished (or were already
        recovered) before the crash, ≈ the reference JobTracker's
        retired-jobs cache backed by completed-job history. Returns a
        client-shaped status dict; for a job an EARLIER master already
        resubmitted, ``recovered_as`` names the successor id to chase.
        None when this job's history holds no outcome."""
        if not self.dir:
            return None
        self.flush()
        path = os.path.join(self.dir, f"{job_id}.jsonl")
        if not os.path.exists(path):
            return None
        submitted: "dict | None" = None
        outcome: "dict | None" = None
        for ev in self.read(path):
            kind = ev.get("event")
            if kind == "JOB_SUBMITTED":
                submitted = ev
            elif kind in ("JOB_FINISHED", "JOB_RECOVERED",
                          "JOB_RECOVERY_FAILED"):
                outcome = ev
        if outcome is None:
            return None
        if outcome["event"] == "JOB_RECOVERED":
            return {"job_id": job_id,
                    "recovered_as": outcome.get("new_job_id"), }
        #: the submit-time conf, for the caller's job-view ACL check
        #: (popped before the status goes on the wire)
        acl_conf = (submitted or {}).get("conf") or {}
        n_maps = int((submitted or {}).get("num_maps", 0) or 0)
        n_reduces = int((submitted or {}).get("num_reduces", 0) or 0)
        if outcome["event"] == "JOB_FINISHED":
            state = str(outcome.get("state", "SUCCEEDED"))
            error = str(outcome.get("error", "") or "")
        else:   # JOB_RECOVERY_FAILED
            state = "FAILED"
            error = (f"recovery failed after a master restart: "
                     f"{outcome.get('error', '')}")
        done = state == "SUCCEEDED"
        return {
            "job_id": job_id, "state": state, "priority": "NORMAL",
            "map_progress": 1.0 if done else 0.0,
            "reduce_progress": 1.0 if done else 0.0,
            "finished_maps": n_maps if done else 0,
            "finished_tpu_maps": int(
                outcome.get("finished_tpu_maps", 0) or 0),
            "finished_cpu_maps": int(
                outcome.get("finished_cpu_maps", 0) or 0),
            "num_maps": n_maps, "num_reduces": n_reduces,
            "cpu_map_mean_time": float(
                outcome.get("cpu_map_mean_time", 0.0) or 0.0),
            "tpu_map_mean_time": float(
                outcome.get("tpu_map_mean_time", 0.0) or 0.0),
            "acceleration_factor": float(
                outcome.get("acceleration_factor", 0.0) or 0.0),
            "placement_seq": "", "tpu_disabled": False,
            "tpu_demoted_tips": 0,
            "error": error,
            "retired": True,   # served from history, not a live JIP
            "_acl_conf": acl_conf,
        }

    def job_finished(self, jip: Any) -> None:
        self._write(str(jip.job_id), {
            "event": "JOB_FINISHED",
            "job_id": str(jip.job_id),
            "state": jip.state,
            "wall_time": (jip.finish_time or time.time()) - jip.start_time,
            "finished_cpu_maps": jip.finished_cpu_maps,
            "finished_tpu_maps": jip.finished_tpu_maps,
            "cpu_map_mean_time": jip.cpu_map_mean_time(),
            "tpu_map_mean_time": jip.tpu_map_mean_time(),
            "acceleration_factor": jip.acceleration_factor(),
            # the assignment-order backend series + stamps: the hybrid
            # convergence curve, plottable from the history file alone
            "placement": jip.placement_timeline(),
            "error": jip.error,
        })

    def task_event(self, job_id: str, event: str, **fields: Any) -> None:
        self._write(job_id, {"event": event, **fields})

    # ------------------------------------------------------ stats rollup

    def metrics_path(self, job_id: str) -> "str | None":
        """Where the job's stats rollup lives, next to its event log."""
        if not self.dir:
            return None
        return os.path.join(self.dir, f"metrics-{job_id}.json")

    def write_job_metrics(self, jip: Any) -> "str | None":
        """One-shot per-job stats rollup written at finalization:
        counters plus exact latency percentiles and the TPU-vs-CPU
        task-time split. The machine-readable substrate for ``tpumr job
        stats`` today and for affinity/critical-path scheduling to mine
        later — the history event log answers "what happened", this
        answers "how fast"."""
        path = self.metrics_path(str(jip.job_id))
        if path is None:
            return None
        os.makedirs(self.dir, exist_ok=True)
        rollup = job_metrics_rollup(jip)
        tmp = path + ".tmp"
        with self._lock:
            with open(tmp, "w") as f:
                json.dump(rollup, f, indent=2, default=str)
            os.replace(tmp, path)   # readers never see a torn rollup
        return path

    def read_job_metrics(self, job_id: str) -> "dict | None":
        path = self.metrics_path(job_id)
        if path is None or not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    @staticmethod
    def read(path: str) -> list[dict]:
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]


def job_metrics_rollup(jip: Any) -> dict:
    """Build the stats rollup from a (terminal) JobInProgress. Exact
    percentiles — the job kept every successful attempt's runtime — and
    the task-time split from those same raw samples (NOT the scheduler's
    profile sums, which deliberately unwind on TPU quarantine)."""
    from tpumr.metrics.histogram import exact_percentiles
    with jip.lock:
        map_rts = list(jip.map_runtimes)
        reduce_rts = list(jip.reduce_runtimes)
        dropped = jip.runtimes_dropped
        counters = jip.counters.to_dict()
        state = jip.state
        finish = jip.finish_time
    tpu = [r for r, on_tpu in map_rts if on_tpu]
    cpu = [r for r, on_tpu in map_rts if not on_tpu]
    tpu_s, cpu_s = sum(tpu), sum(cpu)
    map_task_s = tpu_s + cpu_s
    observed_accel = ((cpu_s / len(cpu)) / (tpu_s / len(tpu))
                      if tpu and cpu and tpu_s > 0 else 0.0)
    return {
        "job_id": str(jip.job_id),
        "job_name": str(jip.conf.get("mapred.job.name", "") or ""),
        "state": state,
        "wall_time": (finish or time.time()) - jip.start_time,
        "num_maps": len(jip.maps),
        "num_reduces": len(jip.reduces),
        "map_latency": exact_percentiles([r for r, _ in map_rts]),
        "map_latency_tpu": exact_percentiles(tpu),
        "map_latency_cpu": exact_percentiles(cpu),
        "reduce_latency": exact_percentiles(reduce_rts),
        "task_time_split": {
            "tpu_map_s": tpu_s,
            "cpu_map_s": cpu_s,
            "reduce_s": sum(reduce_rts),
            "tpu_fraction_of_map_time":
                tpu_s / map_task_s if map_task_s > 0 else 0.0,
        },
        "acceleration_factor_profiled": jip.acceleration_factor(),
        "acceleration_factor_observed": observed_accel,
        "finished_tpu_maps": len(tpu),
        "finished_cpu_maps": len(cpu),
        "runtime_samples_dropped": dropped,
        "counters": counters,
    }
