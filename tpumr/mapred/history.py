"""Job history — structured event log.

≈ ``org.apache.hadoop.mapred.JobHistory`` (reference: src/mapred/org/apache/
hadoop/mapred/JobHistory.java, 2703 LoC — field-encoded line format parsed
by HistoryViewer/rumen). Re-designed as JSON-lines per job under
``tpumr.history.dir`` (one self-describing event per line), which serves the
same consumers: post-hoc job analysis, the web status JSON, and recovery
replay. Backend placement is a first-class field on every task event —
the reference's GPU observability was log-grep only (SURVEY.md §5).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any


def _json_safe(v: Any) -> bool:
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False


class JobHistory:
    def __init__(self, conf: Any) -> None:
        self.dir = conf.get("tpumr.history.dir") if conf else None
        self._lock = threading.Lock()

    def _write(self, job_id: str, event: dict) -> None:
        if not self.dir:
            return
        os.makedirs(self.dir, exist_ok=True)
        event["ts"] = time.time()
        with self._lock:
            with open(os.path.join(self.dir, f"{job_id}.jsonl"), "a") as f:
                f.write(json.dumps(event) + "\n")

    def job_submitted(self, jip: Any) -> None:
        self._write(str(jip.job_id), {
            "event": "JOB_SUBMITTED",
            "job_id": str(jip.job_id),
            "job_name": jip.conf.get("mapred.job.name", ""),
            "num_maps": jip.num_maps,
            "num_reduces": jip.num_reduces,
            "kernel": jip.conf.get("tpumr.map.kernel"),
            "priority": jip.priority,
            # full submission payload so a restarted master can replay the
            # job (≈ RecoveryManager reading the job-info staging file)
            "conf": {k: v for k, v in jip.conf.items()
                     if _json_safe(v)},
            # keys whose values can't ride the wire (in-process class
            # objects): recovery refuses to replay such jobs rather than
            # resubmitting them broken
            "conf_dropped": sorted(k for k, v in jip.conf.items()
                                   if not _json_safe(v)),
            "splits": [t.split for t in jip.maps],
        })

    def job_recovered(self, old_job_id: str, new_job_id: str) -> None:
        """Marks the interrupted job as resubmitted (so a second restart
        doesn't replay it again)."""
        self._write(old_job_id, {"event": "JOB_RECOVERED",
                                 "job_id": old_job_id,
                                 "new_job_id": new_job_id})

    def incomplete_jobs(self) -> list[dict]:
        """JOB_SUBMITTED events of jobs with no terminal/recovered marker —
        the restart-recovery work list (≈ RecoveryManager.recover,
        JobTracker.java:1203)."""
        import glob
        if not self.dir:
            return []
        out = []
        for path in sorted(glob.glob(os.path.join(self.dir, "*.jsonl"))):
            submitted = None
            finished = False
            priority = None
            for ev in self.read(path):
                kind = ev.get("event")
                if kind == "JOB_SUBMITTED":
                    submitted = ev
                elif kind in ("JOB_FINISHED", "JOB_RECOVERED",
                              "JOB_RECOVERY_FAILED"):
                    finished = True
                elif kind == "JOB_PRIORITY_CHANGED":
                    priority = ev.get("priority")
            if submitted is not None and not finished \
                    and submitted.get("conf") is not None:
                if priority:
                    # replay runtime priority changes into the conf the
                    # recovery resubmits — a restart must not silently
                    # revert `job -set-priority`
                    submitted["conf"]["mapred.job.priority"] = priority
                out.append(submitted)
        return out

    def job_finished(self, jip: Any) -> None:
        self._write(str(jip.job_id), {
            "event": "JOB_FINISHED",
            "job_id": str(jip.job_id),
            "state": jip.state,
            "wall_time": (jip.finish_time or time.time()) - jip.start_time,
            "finished_cpu_maps": jip.finished_cpu_maps,
            "finished_tpu_maps": jip.finished_tpu_maps,
            "cpu_map_mean_time": jip.cpu_map_mean_time(),
            "tpu_map_mean_time": jip.tpu_map_mean_time(),
            "acceleration_factor": jip.acceleration_factor(),
            # the assignment-order backend series + stamps: the hybrid
            # convergence curve, plottable from the history file alone
            "placement": jip.placement_timeline(),
            "error": jip.error,
        })

    def task_event(self, job_id: str, event: str, **fields: Any) -> None:
        self._write(job_id, {"event": event, **fields})

    @staticmethod
    def read(path: str) -> list[dict]:
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]
