"""Reduce-side execution: shuffle fetch → merge → group → reduce → commit.

≈ ``org.apache.hadoop.mapred.ReduceTask`` (reference: src/mapred/org/apache/
hadoop/mapred/ReduceTask.java, 2930 LoC): ``ReduceCopier`` parallel fetchers
(:659), in-memory vs on-disk shuffle under a RAM budget (:1080), merge sort
phase (:399-409), then runOldReducer (:478). Here a fetch is a callable
returning one map's partition segment (local file read in LocalJobRunner /
mini-cluster; TCP shuffle client in the distributed runtime), the merge is a
lazy k-way heap merge over raw-key streams, and grouping uses the job's
output-key comparator — preserving the secondary-sort seam.
"""

from __future__ import annotations

import tempfile
import time
from typing import Any, Callable, Iterable, Iterator

from tpumr.core.counters import TaskCounter
from tpumr.io.writable import deserialize
from tpumr.mapred.api import OutputCollector, Reporter
from tpumr.mapred.output_formats import FileOutputCommitter
from tpumr.mapred.task import Task
from tpumr.utils.reflection import new_instance

#: A fetcher yields one map output's (kbytes, vbytes) stream for a partition.
FetchFn = Callable[[int, int], Iterable[tuple[bytes, bytes]]]


def run_reduce_task(conf: Any, task: Task, fetch: FetchFn,
                    reporter: Reporter | None = None) -> "dict | None":
    """Execute one reduce attempt. ``fetch(map_index, partition)`` returns the
    sorted segment of map ``map_index`` for this reduce's partition.

    Returns the streamed-handoff registration payload ({path, index,
    partition, records}) when this stage tees its output for a
    downstream pipeline stage, else None — the tracker registers the
    payload with its shuffle server AFTER the attempt wins the commit.
    """
    reporter = reporter or Reporter()
    from tpumr.mapred.map_task import localize_task_conf
    conf = localize_task_conf(conf, task)
    from tpumr.utils.fi import maybe_fail
    maybe_fail("reduce.task", conf)
    comparator = conf.get_output_key_comparator()
    sk = comparator.sort_key
    grouping = conf.get_output_value_grouping_comparator()
    gk = grouping.sort_key if grouping is not None else sk

    # shuffle: the copy phase ≈ ReduceCopier.fetchOutputs. Three source
    # shapes (newest first):
    #  - ChunkFetch (has .chunk_bytes / is RemoteChunkSource): parallel
    #    RAM-budgeted ShuffleCopier over chunked tracker RPC;
    #  - SegmentSource (has .segments): pre-localized lazy spill views
    #    (LocalJobRunner) — nothing copied, nothing materialized;
    #  - legacy FetchFn callable: sequential whole-segment iterables
    #    (kept for tests and custom fetchers).
    from tpumr.core import tracing
    from tpumr.mapred.shuffle_copier import ShuffleCopier
    segments: list[Iterable[tuple[bytes, bytes]]]
    closeable: list[Any] = []
    tmp_spill_dir: str | None = None
    if hasattr(fetch, "segments"):
        segments = list(fetch.segments(task.partition))
        closeable = list(segments)
    try:
        if hasattr(fetch, "chunk_bytes"):
            spill_dir = conf.get("tpumr.task.local.dir")
            if not spill_dir:
                spill_dir = tmp_spill_dir = tempfile.mkdtemp(
                    prefix=f"shuffle-{task.attempt_id}-")
            # the fetch-failure seam rides on the source: trackers /
            # isolated children wire on_fetch_failure to the umbilical
            # report, so a lost map output stalls (and recovers) this
            # reduce instead of failing it
            with tracing.span("reduce:shuffle",
                              num_maps=task.num_maps) as s:
                copier = ShuffleCopier(conf, fetch, task.num_maps,
                                       task.partition, spill_dir, reporter,
                                       on_fetch_failure=getattr(
                                           fetch, "on_fetch_failure", None))
                segments = copier.copy_all()
                if s is not None:
                    s.set(in_memory=copier.copied_in_memory,
                          on_disk=copier.spilled_to_disk,
                          mem_merges=copier.inmem_merges,
                          disk_merges=copier.disk_merges,
                          fetch_failures=copier.fetch_failures)
            closeable = list(segments)
        elif not hasattr(fetch, "segments"):
            segments = [fetch(m, task.partition)
                        for m in range(task.num_maps)]
        with tracing.span("reduce:merge_reduce", segments=len(segments)):
            return _run_reduce_phase(conf, task, segments, sk, gk,
                                     reporter)
    finally:
        # everything after the copy phase — even reducer/output SETUP —
        # must release shuffle resources (RAM budget, disk spills) or a
        # failing-and-retried attempt leaks a full set per try
        for seg in closeable:
            try:
                seg.close()  # releases RAM budget / deletes shuffle spills
            except Exception:  # noqa: BLE001 — cleanup must not mask
                pass
        if tmp_spill_dir is not None:
            import shutil
            shutil.rmtree(tmp_spill_dir, ignore_errors=True)


def _run_reduce_phase(conf: Any, task: Task,
                      segments: "list[Iterable[tuple[bytes, bytes]]]",
                      sk: Callable, gk: Callable,
                      reporter: Reporter) -> "dict | None":
    """Merge → group → reduce → commit, over already-copied segments."""
    # sort phase: bounded-fan-in merge ≈ Merger.merge honoring
    # io.sort.factor (ReduceTask.java:399-409): a wide shuffle runs
    # intermediate passes (merge:pass spans, MERGE_PASSES counter) so
    # open streams / heap entries never exceed the factor
    from tpumr.io import merger as merge_engine
    engine = merge_engine.BoundedMerge(
        segments, sk, conf.get_int("io.sort.factor", 10),
        run_dir=conf.get("tpumr.task.local.dir") or None,
        reporter=reporter, prefix=f"reduce-p{task.partition}")
    try:
        return _reduce_merged(conf, task, iter(engine), gk, reporter)
    finally:
        engine.close()


def _reduce_merged(conf: Any, task: Task,
                   merged: "Iterator[tuple[bytes, bytes]]",
                   gk: Callable, reporter: Reporter) -> "dict | None":

    # reduce phase — work dir lands in conf BEFORE the reducer is
    # configured so lib.MultipleOutputs works from configure() onward
    committer = FileOutputCommitter(conf)
    wd = committer.setup_task(str(task.attempt_id))
    conf.set("tpumr.task.work.dir", wd)
    reducer_cls = conf.get_reducer_class()
    from tpumr.mapred.api import IdentityReducer
    reducer = new_instance(reducer_cls or IdentityReducer, conf)
    out_fmt = new_instance(conf.get_output_format(), conf)
    writer = out_fmt.get_record_writer(conf, wd, task.partition)

    c_out = reporter.counters.counter(TaskCounter.FRAMEWORK_GROUP,
                                      TaskCounter.REDUCE_OUTPUT_RECORDS)

    # streamed stage handoff (pipeline engine): tee every emitted
    # record into a single-partition IFile the tracker serves over the
    # shuffle wire — downstream maps fetch it instead of re-reading
    # the committed part file from DFS. None for non-pipeline jobs and
    # wherever there is no serving side (LocalJobRunner).
    from tpumr.pipeline.handoff import HandoffWriter
    handoff = HandoffWriter.open_for(conf, task)

    if handoff is None:
        def emit(k: Any, v: Any) -> None:
            c_out.increment()
            writer.write(k, v)
    else:
        def emit(k: Any, v: Any) -> None:
            c_out.increment()
            writer.write(k, v)
            handoff.append(k, v)

    collector = OutputCollector(emit)
    ok = False
    try:
        # optional seam: a reducer may take the collector up front so its
        # lifecycle (new-API setup/cleanup) runs even for zero-group
        # partitions; inside the try so a raising setup still closes the
        # writer and the reducer
        begin = getattr(reducer, "begin_task", None)
        if begin is not None:
            begin(collector, reporter)
        for key, values in group_by_key(merged, gk, reporter):
            reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                  TaskCounter.REDUCE_INPUT_GROUPS)
            reducer.reduce(key, values, collector, reporter)
            # drain any unconsumed values so grouping stays aligned
            for _ in values:
                pass
        ok = True
    finally:
        # failed/killed attempts tear BOTH the reducer and the writer
        # down through their abort seams when they have one: a reducer
        # with side effects in close() (KMeansCentroidUpdateReducer
        # publishing next-round state) must not publish from a
        # partially-fed run — a killed speculative twin's close()
        # would otherwise overwrite the winner's complete artifact
        # with partial aggregates. Plain close() remains the cleanup
        # path for reducers without the seam.
        r_abort = None if ok else getattr(reducer, "abort", None)
        (r_abort or reducer.close)()
        # file writers are naturally safe (the committer never
        # promotes a failed attempt's temp file) but direct-write
        # formats (DBOutputFormat) must not flush a failed task's buffer
        abort = None if ok else getattr(writer, "abort", None)
        (abort or writer.close)()
        if handoff is not None and not ok:
            handoff.abort()   # a failed attempt's tee must not linger
    if handoff is not None:
        return handoff.finish(task.partition)
    return None


def group_by_key(stream: Iterator[tuple[bytes, bytes]],
                 sort_key: Callable[[bytes], Any],
                 reporter: Reporter) -> Iterator[tuple[Any, Iterator[Any]]]:
    """Group a sorted raw stream into (key, lazy values iterator) pairs —
    ≈ ReduceTask.ValuesIterator. Values are deserialized lazily; the caller
    must finish (or the driver drains) each group before the next."""
    it = iter(stream)
    try:
        first = next(it)
    except StopIteration:
        return
    pending: list[tuple[bytes, bytes] | None] = [first]
    c_in = reporter.counters.counter(TaskCounter.FRAMEWORK_GROUP,
                                     TaskCounter.REDUCE_INPUT_RECORDS)

    while pending[0] is not None:
        head = pending[0]
        group_sk = sort_key(head[0])
        key = deserialize(head[0])

        def values() -> Iterator[Any]:
            while pending[0] is not None and sort_key(pending[0][0]) == group_sk:
                kb, vb = pending[0]
                c_in.increment()
                try:
                    pending[0] = next(it)
                except StopIteration:
                    pending[0] = None
                yield deserialize(vb)

        vals = values()
        yield key, vals
        # ensure alignment if the reducer didn't consume everything
        for _ in vals:
            pass


def local_fetch_factory(map_outputs: "list[tuple[str, dict]]"):
    """Segment source over same-process map outputs (LocalJobRunner path):
    lazy spill-file views — see shuffle_copier.LocalSegmentSource."""
    from tpumr.mapred.shuffle_copier import LocalSegmentSource
    return LocalSegmentSource(map_outputs)
