"""Device-shuffled reduce — the MapReduce shuffle+sort as ICI collectives.

The reference's shuffle/sort is host machinery end to end: R reduce tasks
each run parallel HTTP fetchers against every map's spill file
(ReduceTask.java:659 ReduceCopier ↔ TaskTracker.java:4050 MapOutputServlet)
and k-way-merge on disk (:399-409). On a TPU mesh that entire exchange is
ONE ``all_to_all`` and the merge is a per-device vectorized sort — so this
mode re-plans the reduce phase as a single *gang task* that owns the host's
device mesh:

  map tasks (CPU or TPU, unchanged) → **dense map output** (fixed-width
  key/value byte arrays, no sort/spill/partition — the device does both) →
  one device-reduce task: stage all map outputs onto the mesh →
  ``device_partition_sort`` (range partition from sampled splitters, ICI
  all-to-all, per-device lexsort — tpumr.parallel.device_sort) → host
  writes the R range-ordered part files through the normal OutputFormat/
  OutputCommitter path.

Opt-in per job: ``conf.set_device_shuffle(key_bytes, value_bytes)``; keys
and values must be fixed-width ``bytes`` (the device-sortable contract,
SURVEY.md §7 — terasort's 10+90 layout is the canonical fit). The reduce
phase collapses to one task; the original reduce count becomes the number
of output ranges (``part-*`` files), preserving the job's output shape.
Capacity overflow in the exchange retries with doubled buckets and finally
falls back to a host numpy sort (the reference's disk-spill fallback role)
— never wrong output, only a slower path.

Why map outputs come back to the host before staging: map tasks and the
reduce gang task are separate slots, possibly separate processes; the
hand-off rides the same host shuffle-serving seam as the reference
(MapOutputServlet role). The *exchange and sort* — the O(N log N) part the
reference does over HTTP + disk merges — run on device.
"""

from __future__ import annotations

import os
import struct
import time
from typing import Any, Callable

import numpy as np

from tpumr.core.counters import BackendCounter, TaskCounter
from tpumr.mapred.api import OutputCollector, Reporter
from tpumr.mapred.output_formats import FileOutputCommitter
from tpumr.mapred.task import Task
from tpumr.utils.reflection import new_instance

#: job conf keys
DEVICE_SHUFFLE_KEY = "tpumr.shuffle.device"
KEY_BYTES_KEY = "tpumr.shuffle.device.key.bytes"
VALUE_BYTES_KEY = "tpumr.shuffle.device.value.bytes"
RANGES_KEY = "tpumr.shuffle.device.ranges"
CAPACITY_KEY = "tpumr.shuffle.device.capacity"

_MAGIC = b"TDSH"
_HEADER = struct.Struct(">4sIHH")  # magic, n, klen, vlen


def is_device_shuffle(conf: Any) -> bool:
    return bool(conf.get_boolean(DEVICE_SHUFFLE_KEY, False))


def prepare_device_shuffle_job(conf: Any) -> None:
    """Submission-side re-plan (JobClient + LocalJobRunner): the reduce
    phase becomes ONE gang task; the requested reduce count survives as the
    output range count so the job still produces R part files."""
    if not is_device_shuffle(conf):
        return
    if conf.get_int(KEY_BYTES_KEY, 0) <= 0 or \
            conf.get_int(VALUE_BYTES_KEY, 0) < 0:
        raise ValueError(
            f"device shuffle needs fixed record widths: set {KEY_BYTES_KEY}"
            f" / {VALUE_BYTES_KEY} (JobConf.set_device_shuffle)")
    r = conf.num_reduce_tasks
    if r == 0:
        raise ValueError("device shuffle requires a reduce phase "
                         "(num_reduce_tasks >= 1)")
    # the device sorts raw bytes ascending — a custom key order or a
    # grouping comparator would silently change output order/grouping
    # relative to the host path, so reject rather than diverge
    from tpumr.mapred.api import RawComparator
    cmp_cls = conf.get_class("mapred.output.key.comparator.class")
    if cmp_cls is not None and cmp_cls is not RawComparator:
        raise ValueError(
            f"device shuffle sorts raw bytes ascending; output key "
            f"comparator {cmp_cls.__name__} is not supported — use "
            f"RawComparator or the host shuffle")
    if conf.get_class("mapred.output.value.groupfn.class") is not None:
        raise ValueError("device shuffle does not support a grouping "
                         "comparator (secondary sort) — use the host "
                         "shuffle")
    if not conf.get(RANGES_KEY):
        conf.set(RANGES_KEY, r)
    conf.set_num_reduce_tasks(1)


class DenseMapOutputBuffer:
    """Map-side collector for device-shuffled jobs: fixed-width records
    appended to flat byte buffers, written as ONE dense file — no
    partitioning, no sort, no spill (the device does all three). Replaces
    MapOutputBuffer at the same seam in ``run_map_task``."""

    def __init__(self, conf: Any, local_dir: str, reporter: Reporter) -> None:
        self.klen = conf.get_int(KEY_BYTES_KEY, 0)
        self.vlen = conf.get_int(VALUE_BYTES_KEY, 0)
        self.local_dir = local_dir
        self.reporter = reporter
        self._keys = bytearray()
        self._values = bytearray()
        self._n = 0
        os.makedirs(local_dir, exist_ok=True)

    def collect(self, key: Any, value: Any) -> None:
        if not isinstance(key, (bytes, bytearray)) or len(key) != self.klen:
            raise ValueError(
                f"device shuffle requires {self.klen}-byte keys, got "
                f"{type(key).__name__}[{len(key) if hasattr(key, '__len__') else '?'}]")
        if not isinstance(value, (bytes, bytearray)) or \
                len(value) != self.vlen:
            raise ValueError(
                f"device shuffle requires {self.vlen}-byte values, got "
                f"{type(value).__name__}")
        self._keys += key
        self._values += value
        self._n += 1
        self.reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                   TaskCounter.MAP_OUTPUT_RECORDS)
        self.reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                   TaskCounter.MAP_OUTPUT_BYTES,
                                   self.klen + self.vlen)

    def collect_fixed_batch(self, keys: np.ndarray,
                            values: np.ndarray) -> None:
        """Bulk ingest for the identity-map fast path: ``[n, klen]`` /
        ``[n, vlen]`` uint8 arrays appended in two copies, with the same
        width validation and counter accounting as n ``collect`` calls."""
        if keys.ndim != 2 or keys.shape[1] != self.klen:
            raise ValueError(f"device shuffle requires {self.klen}-byte "
                             f"keys, got array {keys.shape}")
        if values.ndim != 2 or values.shape[1] != self.vlen:
            raise ValueError(f"device shuffle requires {self.vlen}-byte "
                             f"values, got array {values.shape}")
        if keys.shape[0] != values.shape[0]:
            raise ValueError("key/value row counts differ")
        n = int(keys.shape[0])
        self._keys += keys.astype(np.uint8, copy=False).tobytes()
        self._values += values.astype(np.uint8, copy=False).tobytes()
        self._n += n
        self.reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                   TaskCounter.MAP_OUTPUT_RECORDS, n)
        self.reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                   TaskCounter.MAP_OUTPUT_BYTES,
                                   n * (self.klen + self.vlen))

    def flush(self) -> tuple[str, dict]:
        path = os.path.join(self.local_dir, "file.dense")
        with open(path, "wb") as f:
            f.write(_HEADER.pack(_MAGIC, self._n, self.klen, self.vlen))
            f.write(bytes(self._keys))
            f.write(bytes(self._values))
        return path, {"dense": True, "n": self._n,
                      "klen": self.klen, "vlen": self.vlen}


def parse_dense_bytes(data: bytes) -> tuple[np.ndarray, np.ndarray]:
    """(keys [n, klen] u8, values [n, vlen] u8) from dense-output bytes —
    the serving tracker ships the file verbatim (header is self-describing)
    so there is no reserialize hop on the hot shuffle path."""
    magic, n, klen, vlen = _HEADER.unpack_from(data)
    if magic != _MAGIC:
        raise ValueError("not a dense map output (bad magic)")
    off = _HEADER.size
    keys = np.frombuffer(data, np.uint8, n * klen, off).reshape(n, klen)
    values = np.frombuffer(data, np.uint8, n * vlen,
                           off + n * klen).reshape(n, vlen)
    return keys, values


def read_dense_output(path: str) -> tuple[np.ndarray, np.ndarray]:
    """(keys, values) arrays from a dense map output file."""
    with open(path, "rb") as f:
        return parse_dense_bytes(f.read())


#: a dense fetch returns one map's (keys, values) arrays
DenseFetchFn = Callable[[int], tuple[np.ndarray, np.ndarray]]


def _load_splitters(conf: Any, keys: np.ndarray, num_ranges: int,
                    klen: int) -> np.ndarray:
    """Range cut points [r-1, klen] u8: the job's TotalOrderPartitioner
    file when present (terasort writes one), else sampled from the staged
    keys themselves (device mode is self-contained — ≈ TeraInputFormat's
    in-job sampling)."""
    from tpumr.mapred.total_order import PARTITION_PATH_KEY
    path = conf.get(PARTITION_PATH_KEY)
    if path:
        from tpumr.fs import get_filesystem
        from tpumr.io.writable import deserialize
        cuts = deserialize(get_filesystem(path, conf).read_bytes(path))
        good = [c for c in cuts
                if isinstance(c, (bytes, bytearray)) and len(c) == klen]
        if len(good) == len(cuts) and cuts:
            return np.frombuffer(b"".join(good), np.uint8).reshape(-1, klen)
    if num_ranges <= 1 or keys.shape[0] == 0:
        return np.zeros((0, klen), np.uint8)
    n = keys.shape[0]
    sample_idx = np.linspace(0, n - 1, min(n, 64 * num_ranges)).astype(int)
    samp = keys[sample_idx]
    order = np.lexsort(tuple(samp[:, c] for c in range(klen - 1, -1, -1)))
    samp = samp[order]
    cut_idx = [min(len(samp) - 1, round(i * len(samp) / num_ranges))
               for i in range(1, num_ranges)]
    return samp[cut_idx]


def _range_boundaries(sorted_keys: np.ndarray, splitters: np.ndarray,
                      lo_range: int, hi_range: int) -> list[int]:
    """Split one device's key-sorted shard into its ranges: boundary after
    range i = #keys <= splitters[i] (vectorized lexicographic count —
    consistent with compute_dest's 'equal goes low' convention). Cut lists
    can be SHORT (write_partition_file dedups duplicate samples): a missing
    splitter acts as +inf, leaving the top ranges empty — same tolerance
    as the host TotalOrderPartitioner."""
    from tpumr.parallel.device_sort import _lex_gt, key_columns
    n, klen = sorted_keys.shape
    if n == 0:
        return [0] * (hi_range - lo_range - 1)
    kcols = key_columns(sorted_keys, klen)
    scols = key_columns(splitters, klen) if len(splitters) else None
    bounds = []
    for i in range(lo_range, hi_range - 1):
        if scols is None or i >= len(scols):
            bounds.append(n)  # +inf splitter: everything stays below
        else:
            bounds.append(int(n - _lex_gt(kcols, scols[i]).sum()))
    return bounds


def run_device_reduce(conf: Any, task: Task, dense_fetch: DenseFetchFn,
                      reporter: Reporter | None = None) -> None:
    """Execute the reduce gang task: fetch every map's dense output, run
    the device partition+exchange+sort, apply the job's reducer over each
    range's sorted stream, write R part files, one commit."""
    reporter = reporter or Reporter()
    from tpumr.mapred.map_task import localize_task_conf
    conf = localize_task_conf(conf, task)
    from tpumr.utils.fi import maybe_fail
    maybe_fail("reduce.task", conf)

    klen = conf.get_int(KEY_BYTES_KEY, 0)
    vlen = conf.get_int(VALUE_BYTES_KEY, 0)
    num_ranges = conf.get_int(RANGES_KEY, 1)

    # ---- copy phase (host, ≈ ReduceCopier.fetchOutputs)
    t0 = time.monotonic()
    key_parts, val_parts = [], []
    for m in range(task.num_maps):
        k, v = dense_fetch(m)
        if k.shape[1] != klen or v.shape[1] != vlen:
            raise ValueError(f"map {m} dense output widths "
                             f"({k.shape[1]},{v.shape[1]}) != conf "
                             f"({klen},{vlen})")
        key_parts.append(k)
        val_parts.append(v)
    keys = np.concatenate(key_parts) if key_parts else \
        np.zeros((0, klen), np.uint8)
    values = np.concatenate(val_parts) if val_parts else \
        np.zeros((0, vlen), np.uint8)
    n = keys.shape[0]
    reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                          TaskCounter.REDUCE_INPUT_RECORDS, n)
    records = np.concatenate([keys, values], axis=1)
    splitters = _load_splitters(conf, keys, num_ranges, klen)

    # ---- exchange + sort phase (device)
    shards = None
    overflow = 0
    if n > 0:
        import jax
        from tpumr.parallel.jaxruntime import configure_persistent_cache
        from tpumr.parallel.mesh import make_mesh
        from tpumr.parallel.device_sort import device_partition_sort
        configure_persistent_cache(conf)
        mesh = make_mesh(devices=jax.local_devices())
        capacity = conf.get_int(CAPACITY_KEY, 0) or None
        shards, overflow = device_partition_sort(
            mesh, records, klen, splitters, num_ranges, capacity=capacity)
        # liveness tick for the bench wedge watchdog: the gang sort is
        # one long device stretch with no other transfer chokepoint
        from tpumr.utils import progress
        progress.tick(int(records.nbytes), "gang-sort")
        if shards is not None:  # count only records the device actually moved
            reporter.incr_counter(BackendCounter.GROUP,
                                  BackendCounter.TPU_SHUFFLE_RECORDS, n)
            reporter.incr_counter(BackendCounter.GROUP,
                                  BackendCounter.TPU_SHUFFLE_BYTES,
                                  int(records.nbytes))
            if jax.default_backend() != "cpu":
                reporter.incr_counter(BackendCounter.GROUP,
                                      BackendCounter.DEVICE_SORT_ON_ACCEL)
    if shards is None:
        # host fallback: full numpy lexsort, then the same range split
        # (≈ the disk-spill fallback role; correctness never depends on
        # the device path)
        if n > 0 and overflow:
            reporter.incr_counter(BackendCounter.GROUP,
                                  BackendCounter.SHUFFLE_HOST_FALLBACKS)
        from tpumr.parallel.device_sort import key_columns
        kcols = key_columns(keys, klen) if n else None
        order = np.lexsort(tuple(
            kcols[:, c] for c in range(kcols.shape[1] - 1, -1, -1))) \
            if n else np.zeros(0, int)
        all_sorted = records[order]
        n_dev = 1
        shards = [all_sorted]
    else:
        n_dev = len(shards)
    ranges_per_dev = -(-num_ranges // n_dev)
    reporter.set_status(
        f"device shuffle: {n} records over {n_dev} devices in "
        f"{time.monotonic() - t0:.3f}s (overflow retries seen: {overflow})")

    # ---- reduce + write phase (host, range-ordered part files)
    reducer_cls = conf.get_reducer_class()
    from tpumr.mapred.api import IdentityReducer
    identity = reducer_cls is None or reducer_cls is IdentityReducer
    committer = FileOutputCommitter(conf)
    wd = committer.setup_task(str(task.attempt_id))
    out_fmt = new_instance(conf.get_output_format(), conf)

    def write_range(range_idx: int, rows: np.ndarray) -> None:
        writer = out_fmt.get_record_writer(conf, wd, range_idx)
        try:
            if identity:
                _write_rows(writer, rows, klen, reporter)
            else:
                _reduce_rows(conf, reducer_cls, rows, klen, writer, reporter)
        finally:
            writer.close()

    emitted = set()
    for d in range(n_dev):
        lo_r = d * ranges_per_dev
        hi_r = min((d + 1) * ranges_per_dev, num_ranges)
        if lo_r >= hi_r:
            continue
        shard = shards[d]
        bounds = _range_boundaries(shard[:, :klen], splitters, lo_r, hi_r)
        cuts = [0] + bounds + [shard.shape[0]]
        for i, r in enumerate(range(lo_r, hi_r)):
            write_range(r, shard[cuts[i]:cuts[i + 1]])
            emitted.add(r)
    for r in range(num_ranges):  # ranges on idle devices: empty parts
        if r not in emitted:
            write_range(r, np.zeros((0, klen + vlen), np.uint8))
    # commit is the CALLER's job (tracker: master-gated can_commit;
    # local runner: direct commit_task) — same contract as run_reduce_task


def _write_rows(writer: Any, rows: np.ndarray, klen: int,
                reporter: Reporter) -> None:
    bulk = getattr(writer, "write_fixed_rows", None)
    if bulk is not None:
        bulk(rows, klen)  # vectorized framing — per-record append would
        #                   dominate the whole device-shuffled job
    else:
        kb = rows[:, :klen]
        vb = rows[:, klen:]
        for i in range(rows.shape[0]):
            writer.write(kb[i].tobytes(), vb[i].tobytes())
    reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                          TaskCounter.REDUCE_OUTPUT_RECORDS, rows.shape[0])


def _reduce_rows(conf: Any, reducer_cls: type, rows: np.ndarray, klen: int,
                 writer: Any, reporter: Reporter) -> None:
    """Run the user reducer over the key-sorted rows of one range: groups
    are consecutive equal keys (device sort replaced the merge, grouping
    semantics preserved)."""
    reducer = new_instance(reducer_cls, conf)
    n = rows.shape[0]

    def emit(k: Any, v: Any) -> None:
        reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                              TaskCounter.REDUCE_OUTPUT_RECORDS)
        writer.write(k, v)

    collector = OutputCollector(emit)
    try:
        i = 0
        while i < n:
            key = rows[i, :klen].tobytes()
            j = i
            while j < n and rows[j, :klen].tobytes() == key:
                j += 1
            reporter.incr_counter(TaskCounter.FRAMEWORK_GROUP,
                                  TaskCounter.REDUCE_INPUT_GROUPS)
            values = (rows[t, klen:].tobytes() for t in range(i, j))
            reducer.reduce(key, values, collector, reporter)
            i = j
    finally:
        reducer.close()


def local_dense_fetch(map_outputs: "list[tuple[str, dict] | None]"
                      ) -> DenseFetchFn:
    """In-process fetch over the maps' dense files (LocalJobRunner path)."""

    def fetch(map_index: int) -> tuple[np.ndarray, np.ndarray]:
        ent = map_outputs[map_index]
        assert ent is not None, f"map {map_index} output missing"
        return read_dense_output(ent[0])

    return fetch
