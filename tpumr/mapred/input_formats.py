"""Input formats: split computation + record readers.

≈ ``org.apache.hadoop.mapred.{InputFormat,FileInputFormat,TextInputFormat,
SequenceFileInputFormat}`` and ``mapred/lib/{NLineInputFormat,
CombineFileInputFormat}``. Split sizing follows FileInputFormat.getSplits
(reference: src/mapred/org/apache/hadoop/mapred/FileInputFormat.java):
``split_size = max(min_size, min(goal_size, block_size))``, with block
locality hints from FileSystem.get_block_locations feeding the scheduler's
locality caches.

New for TPU: :class:`DenseInputFormat` — dense numeric datasets split by row
range (DenseSplit); its splits are what the TPU map runner stages into HBM
whole. The reference's GPU config achieved kernel-sized batches by pinning
NLineInputFormat to 1 line per map (conf/mapred-site.xml:14-21); DenseSplit
makes the batch a first-class unit instead.
"""

from __future__ import annotations

from io import BytesIO
from typing import Any, Iterator

import numpy as np

from tpumr.fs.filesystem import FileStatus, FileSystem, Path
from tpumr.io import sequencefile
from tpumr.mapred.split import DenseSplit, FileSplit, InputSplit


class InputFormat:
    def get_splits(self, conf: Any, num_splits: int) -> list[InputSplit]:
        raise NotImplementedError

    def get_record_reader(self, split: InputSplit, conf: Any,
                          reporter: Any = None) -> Iterator[tuple[Any, Any]]:
        raise NotImplementedError


def _hidden(name: str) -> bool:
    return name.startswith("_") or name.startswith(".")


class FileInputFormat(InputFormat):
    """Base: input path listing + block-aligned split computation."""

    splittable = True

    def list_input_files(self, conf: Any) -> list[tuple[FileSystem, FileStatus]]:
        out: list[tuple[FileSystem, FileStatus]] = []
        for p in conf.get_strings("mapred.input.dir"):
            fs = FileSystem.get(p, conf)
            if any(c in p for c in "*?["):
                stats = fs.glob_status(p)
            elif fs.exists(p):
                st = fs.get_status(p)
                stats = fs.list_status(p) if st.is_dir else [st]
            else:
                raise FileNotFoundError(f"input path does not exist: {p}")
            for st in stats:
                if st.is_dir:
                    for sub in fs.list_files(st.path, recursive=True):
                        if not _hidden(sub.path.name):
                            out.append((fs, sub))
                elif not _hidden(st.path.name):
                    out.append((fs, st))
        return out

    def get_splits(self, conf: Any, num_splits: int) -> list[InputSplit]:
        files = self.list_input_files(conf)
        total = sum(st.length for _, st in files)
        goal = max(1, total // max(1, num_splits))
        min_size = conf.get_int("mapred.min.split.size", 1)
        max_size = conf.get_int("mapred.max.split.size", 2**63 - 1)
        splits: list[InputSplit] = []
        for fs, st in files:
            if st.length == 0:
                continue
            if not self.splittable:
                hosts = _hosts(fs, st, 0, st.length)
                splits.append(FileSplit(hosts, str(st.path), 0, st.length))
                continue
            split_size = max(min_size, min(goal, st.block_size, max_size))
            pos = 0
            remaining = st.length
            # FileInputFormat's SPLIT_SLOP: tail smaller than 1.1×split rides
            # along with the last split
            while remaining / split_size > 1.1:
                hosts = _hosts(fs, st, pos, split_size)
                splits.append(FileSplit(hosts, str(st.path), pos, split_size))
                pos += split_size
                remaining -= split_size
            if remaining:
                hosts = _hosts(fs, st, pos, remaining)
                splits.append(FileSplit(hosts, str(st.path), pos, remaining))
        return splits


def _hosts(fs: FileSystem, st: FileStatus, offset: int, length: int) -> list[str]:
    locs = fs.get_block_locations(st.path, offset, length)
    hosts: list[str] = []
    for loc in locs:
        for h in loc.hosts:
            if h not in hosts:
                hosts.append(h)
    return hosts


class LineRecordReader:
    """≈ org.apache.hadoop.mapred.LineRecordReader: a split [start, start+len)
    owns every line that *begins* strictly after start (or at 0), reading past
    the end to finish its final line."""

    def __init__(self, fs: FileSystem, path: str, start: int, length: int,
                 keep_bytes: bool = False) -> None:
        self._f = fs.open(path)
        self._end = start + length
        self._keep_bytes = keep_bytes
        self._pos = start
        self._f.seek(start)
        if start > 0:
            # skip the partial line owned by the previous split
            self._pos += len(self._f.readline())

    def __iter__(self) -> Iterator[tuple[int, Any]]:
        # a line whose first byte sits at pos <= end belongs to this split
        # (the next split discards it as its leading partial line) — the
        # LineRecordReader ownership rule that makes coverage exact
        while self._pos <= self._end:
            line = self._f.readline()
            if not line:
                break
            offset = self._pos
            self._pos += len(line)
            stripped = line.rstrip(b"\r\n")
            yield offset, (stripped if self._keep_bytes
                           else stripped.decode("utf-8", errors="replace"))
        self._f.close()


class TextInputFormat(FileInputFormat):
    """≈ org.apache.hadoop.mapred.TextInputFormat: (byte offset, line)."""

    keep_bytes = False

    def get_record_reader(self, split, conf, reporter=None):
        assert isinstance(split, FileSplit)
        fs = FileSystem.get(split.path, conf)
        return iter(LineRecordReader(fs, split.path, split.start,
                                     split.split_length, self.keep_bytes))

    @staticmethod
    def _read_owned_bytes(split, conf) -> bytes:
        """The split's OWNED byte range under the LineRecordReader
        ownership rule — the subtlest invariant of text splitting, so it
        lives exactly once: skip the partial first line when start > 0,
        own every line beginning at pos <= end (reading past end to
        finish it; a line starting exactly AT end is owned too — the
        next split discards it as its leading partial)."""
        fs = FileSystem.get(split.path, conf)
        with fs.open(split.path) as f:
            f.seek(split.start)
            buf = f.read(split.split_length)
            if split.start > 0:
                nl = buf.find(b"\n")
                if nl < 0:
                    return b""                  # mid-line: owns nothing
                buf = buf[nl + 1:]
            buf += f.readline()
        return buf

    def read_batch(self, split, conf):
        """Whole-split vectorized read for kernel jobs: ONE file read +
        C-speed newline scan instead of 100k+ Python ``readline`` calls.
        Ownership matches :class:`LineRecordReader` exactly (see
        :meth:`_read_owned_bytes`); trailing ``\\r``/``\\n`` stripped
        per line."""
        from tpumr.io.recordbatch import RecordBatch
        assert isinstance(split, FileSplit)
        buf = self._read_owned_bytes(split, conf)
        if not buf:
            return RecordBatch.empty()
        arr = np.frombuffer(buf, dtype=np.uint8)
        nls = np.flatnonzero(arr == 0x0A).astype(np.int64)
        # line spans [start, end): starts = 0 and nl+1; a trailing chunk
        # with no final newline is still a line (EOF case)
        starts = np.concatenate(([0], nls + 1))
        ends = np.concatenate((nls, [arr.shape[0]]))
        if starts[-1] >= arr.shape[0] and len(starts) > 1:
            starts, ends = starts[:-1], ends[:-1]  # buf ended with \n
        # rstrip(b"\r\n"): drop newlines and any trailing CRs per line
        mask = arr != 0x0A
        while True:
            has_cr = (ends > starts) & (arr[np.maximum(ends - 1, 0)] == 0x0D)
            if not has_cr.any():
                break
            ends = ends - has_cr
            mask[ends[has_cr]] = False
        value_data = arr[mask]
        lengths = ends - starts
        offsets = np.zeros(len(lengths) + 1, np.int32)
        np.cumsum(lengths, out=offsets[1:])
        n = len(lengths)
        batch = RecordBatch(np.zeros(0, np.uint8), np.zeros(n + 1, np.int32),
                            value_data, offsets)
        if not self.keep_bytes and (value_data > 0x7F).any():
            # reader parity: TextInputFormat values pass through
            # decode('utf-8', 'replace') — identical to raw bytes for
            # valid UTF-8 (checked strictly with \n separators so a line
            # ending mid-sequence can't be masked by its successor), so
            # only genuinely invalid input pays the per-line fallback
            try:
                batch.joined_values(0x0A).decode("utf-8")
            except UnicodeDecodeError:
                return RecordBatch.from_values(
                    batch.value(i).decode("utf-8", "replace").encode()
                    for i in range(n))
        return batch


class RawTextInputFormat(TextInputFormat):
    """Whole-split text as ONE record: the boundary-corrected buffer
    (same ownership rule as TextInputFormat — skip the leading partial
    line, finish the trailing one) without any line parsing. For
    whitespace-tokenizing kernels (wordcount) newlines are just another
    separator, so per-line machinery is pure overhead — this format
    removes it (measured: the line scan + join cost more than the
    native tokenizer itself). MAP_INPUT_RECORDS counts splits, not
    lines — documented divergence."""

    keep_bytes = True

    def read_batch(self, split, conf):
        from tpumr.io.recordbatch import RecordBatch
        assert isinstance(split, FileSplit)
        buf = self._read_owned_bytes(split, conf)
        if not buf:
            return RecordBatch.empty()
        # zero-copy: the batch's value_data is a view over buf
        return RecordBatch(np.zeros(0, np.uint8),
                           np.zeros(2, np.int32),
                           np.frombuffer(buf, dtype=np.uint8),
                           np.array([0, len(buf)], dtype=np.int32))


class BytesTextInputFormat(TextInputFormat):
    """Like TextInputFormat but values stay raw bytes (terasort rows)."""
    keep_bytes = True


class KeyValueTextInputFormat(TextInputFormat):
    """≈ mapred/KeyValueTextInputFormat.java: each line splits at the
    first separator byte (``key.value.separator.in.input.line``, default
    TAB) into (key, value); a line with no separator becomes (line, "")."""

    # values here are the part AFTER the separator — the whole-line batch
    # fast path would hand kernels the wrong bytes
    read_batch = None

    def get_record_reader(self, split, conf, reporter=None):
        # FIRST BYTE of the configured separator, as the reference does
        # (KeyValueLineRecordReader takes separator.charAt(0)); an empty
        # config value falls back to TAB instead of crashing the task
        sep = (str(conf.get("key.value.separator.in.input.line", "\t"))
               or "\t")[:1]
        for _offset, line in super().get_record_reader(split, conf,
                                                       reporter):
            k, _, v = line.partition(sep)
            yield k, v


class NLineInputFormat(FileInputFormat):
    """≈ mapred/lib/NLineInputFormat.java: one split per N lines — the knob
    the reference's GPU config used to make one map = one kernel launch
    (conf/mapred-site.xml:14-21, mapreduce.job.maps via N=1)."""

    def get_splits(self, conf, num_splits):
        n = conf.get_int("mapred.line.input.format.linespermap", 1)
        splits: list[InputSplit] = []
        for fs, st in self.list_input_files(conf):
            with fs.open(st.path) as f:
                pos = 0
                count = 0
                begin = 0
                for line in f:
                    count += 1
                    pos += len(line)
                    if count == n:
                        splits.append(FileSplit(_hosts(fs, st, begin, pos - begin),
                                                str(st.path), begin, pos - begin))
                        begin = pos
                        count = 0
                if count:
                    splits.append(FileSplit(_hosts(fs, st, begin, pos - begin),
                                            str(st.path), begin, pos - begin))
        return splits

    def get_record_reader(self, split, conf, reporter=None):
        assert isinstance(split, FileSplit)
        fs = FileSystem.get(split.path, conf)
        # NLine splits are exact line ranges: read [start, end) verbatim
        f = fs.open(split.path)
        f.seek(split.start)

        def gen():
            pos = split.start
            end = split.start + split.split_length
            while pos < end:
                line = f.readline()
                if not line:
                    break
                offset = pos
                pos += len(line)
                yield offset, line.rstrip(b"\r\n").decode("utf-8", errors="replace")
            f.close()

        return gen()


class SequenceFileInputFormat(FileInputFormat):
    """≈ org.apache.hadoop.mapred.SequenceFileInputFormat: typed k/v records,
    sync-aligned split reads."""

    def get_record_reader(self, split, conf, reporter=None):
        assert isinstance(split, FileSplit)
        fs = FileSystem.get(split.path, conf)
        f = fs.open(split.path)
        reader = sequencefile.Reader(f)

        def gen():
            try:
                yield from reader.iter_range(split.start,
                                            split.start + split.split_length)
            finally:
                f.close()

        return gen()

    def read_batch(self, split, conf):
        """Whole-split read for kernel jobs — fixed-width bytes records
        (terasort's 10+90 layout) parse as one numpy reshape per block
        (sequencefile._parse_fixed_block); anything else falls back to
        the per-record parser with reader-equivalent value bytes."""
        assert isinstance(split, FileSplit)
        fs = FileSystem.get(split.path, conf)
        with fs.open(split.path) as f:
            return sequencefile.Reader(f).read_batch_range(
                split.start, split.start + split.split_length)


class WholeFileInputFormat(FileInputFormat):
    """One record per file: (path, bytes). Not splittable."""

    splittable = False

    def get_record_reader(self, split, conf, reporter=None):
        assert isinstance(split, FileSplit)
        fs = FileSystem.get(split.path, conf)
        return iter([(split.path, fs.read_bytes(split.path))])


class CombineFileInputFormat(FileInputFormat):
    """≈ mapred/lib/CombineFileInputFormat.java (simplified): packs many
    small whole files into few splits, bounded by mapred.max.split.size."""

    def get_splits(self, conf, num_splits):
        files = self.list_input_files(conf)
        total = sum(st.length for _, st in files)
        target = conf.get_int("mapred.max.split.size", 2**63 - 1)
        if target in (0, 2**63 - 1):
            target = max(1, total // max(1, num_splits))
        splits: list[InputSplit] = []
        cur: list[FileSplit] = []
        cur_bytes = 0
        for fs, st in files:
            cur.append(FileSplit(_hosts(fs, st, 0, st.length), str(st.path),
                                 0, st.length))
            cur_bytes += st.length
            if cur_bytes >= target:
                splits.append(MultiFileSplit(sum((s.locations for s in cur), []),
                                             parts=[(s.path, s.start, s.split_length)
                                                    for s in cur]))
                cur, cur_bytes = [], 0
        if cur:
            splits.append(MultiFileSplit(sum((s.locations for s in cur), []),
                                         parts=[(s.path, s.start, s.split_length)
                                                for s in cur]))
        return splits

    def get_record_reader(self, split, conf, reporter=None):
        assert isinstance(split, MultiFileSplit)

        def gen():
            for path, start, length in split.parts:
                fs = FileSystem.get(path, conf)
                yield from LineRecordReader(fs, path, start, length)

        return gen()

    def read_batch(self, split, conf):
        """Kernel jobs over many small files: one vectorized text batch
        per part, concatenated — no per-line Python."""
        from tpumr.io.recordbatch import RecordBatch
        assert isinstance(split, MultiFileSplit)
        text = TextInputFormat()
        return RecordBatch.concat([
            text.read_batch(FileSplit([], path, start, length), conf)
            for path, start, length in split.parts])


from dataclasses import dataclass, field  # noqa: E402


@dataclass
class MultiFileSplit(InputSplit):
    """≈ mapred/MultiFileSplit.java: several (path, start, length) chunks."""
    parts: list = field(default_factory=list)

    @property
    def length(self) -> int:
        return sum(p[2] for p in self.parts)


# ------------------------------------------------------------ dense (TPU)


def load_dense(fs: FileSystem, path: str) -> np.ndarray:
    """Load a whole .npy array through the FS abstraction."""
    data = fs.read_bytes(path)
    return np.load(BytesIO(data), allow_pickle=False)


def read_npy_header(f: Any) -> tuple[tuple[int, ...], np.dtype, int]:
    """Parse only the npy header: (shape, dtype, data_offset). C-order
    required (we address rows by byte range)."""
    version = np.lib.format.read_magic(f)
    if version == (1, 0):
        shape, fortran, dtype = np.lib.format.read_array_header_1_0(f)
    else:
        shape, fortran, dtype = np.lib.format.read_array_header_2_0(f)
    if fortran:
        raise ValueError("Fortran-order .npy not supported for dense splits")
    return shape, dtype, f.tell()


class DenseInputFormat(InputFormat):
    """Dense numeric input: each input path is a .npy 2-D array; splits are
    row ranges sized so one split = one HBM staging unit (default rows per
    split chosen from tpumr.dense.split.rows or evenly by num_splits).
    Split computation parses only npy headers; readers seek straight to the
    row range — no full-file loads."""

    def get_splits(self, conf, num_splits):
        splits: list[InputSplit] = []
        for p in conf.get_strings("mapred.input.dir"):
            fs = FileSystem.get(p, conf)
            stats = ([fs.get_status(p)] if not fs.get_status(p).is_dir
                     else [s for s in fs.list_files(p, recursive=True)
                           if s.path.name.endswith(".npy")])
            for st in stats:
                with fs.open(st.path) as f:
                    shape, dtype, offset = read_npy_header(f)
                rows = shape[0]
                cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
                row_bytes = cols * dtype.itemsize
                per = conf.get_int("tpumr.dense.split.rows", 0) or \
                    max(1, -(-rows // max(1, num_splits)))
                for start in range(0, rows, per):
                    n = min(per, rows - start)
                    hosts = _hosts(fs, st, offset + start * row_bytes,
                                   n * row_bytes)
                    splits.append(DenseSplit(hosts, str(st.path), start, n,
                                             row_bytes, dtype.str, cols,
                                             offset))
        return splits

    def get_record_reader(self, split, conf, reporter=None):
        """CPU fallback path: one record per row (id, row array). The TPU
        runner bypasses this and calls :meth:`read_batch`. Rows are
        copied per record: read_batch hands out a read-only view (the
        zero-copy staging contract) but user mappers may mutate their
        row in place."""
        batch = self.read_batch(split, conf)
        ids = batch.ids if batch.ids is not None else np.arange(len(batch))
        return iter((int(i), np.array(row)) for i, row in
                    zip(ids, batch.values))

    def read_batch(self, split, conf):
        from tpumr.io.recordbatch import DenseBatch
        assert isinstance(split, DenseSplit)
        fs = FileSystem.get(split.path, conf)
        with fs.open(split.path) as f:
            f.seek(split.data_offset + split.row_start * split.row_bytes)
            raw = f.read(split.num_rows * split.row_bytes)
        # read-only view over the freshly-read buffer: consumers compute
        # from it or device_put it, never mutate — copying would double
        # the memory traffic of exactly the multi-GB staging path
        arr = np.frombuffer(raw, dtype=np.dtype(split.dtype)).reshape(
            split.num_rows, split.cols)
        ids = np.arange(split.row_start, split.row_start + split.num_rows,
                        dtype=np.int64)
        return DenseBatch(arr, ids)
